"""Hybrid engine mode: per-term recompute-vs-stream split (DESIGN.md §28).

The hybrid apply must be BIT-identical to the pure-streamed apply: the
build resolves the full structure exactly as streamed does, stores only
the streamed term subset, and the chunk program re-derives the recompute
terms on device — their amplitudes landing, per exchange bucket, on
exactly the slots the streamed entries left free (provably the full
plan's merged slots).  Plus the fingerprint-v4 contract: a changed
``hybrid_split`` misses the sidecar cache (a partial-term plan is never
misread), a v3-era streamed sidecar misses-and-rebuilds, and a corrupt
streamed chunk in a hybrid plan heals bit-identically.
"""

import os

import jax
import numpy as np
import pytest

from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.utils.config import update_config

from test_operator import build_heisenberg

ATOL, RTOL = 1e-13, 1e-12


def _ndev() -> int:
    return len(jax.devices())


needs_8 = pytest.mark.skipif("_ndev() < 8", reason="needs 8 virtual devices")
needs_4 = pytest.mark.skipif("_ndev() < 4", reason="needs 4 virtual devices")


HYBRID_CONFIGS = [
    # (n, hw, inv, syms, ndev, split) — a |G|>1 chain sector, a trivial
    # group, a complex-character sector (c128 on CPU); splits cover the
    # degenerate ends and a genuinely mixed set
    (12, 6, 1, [([*range(1, 12), 0], 0)], 4, "stream:0,2,5"),
    (12, 6, 1, [([*range(1, 12), 0], 0)], 4, "all-recompute"),
    (12, 6, 1, [([*range(1, 12), 0], 0)], 4, "all-stream"),
    (10, 5, None, (), 4, "stream:1,3"),
    (10, 5, None, [([*range(1, 10), 0], 1)], 4, "stream:0,1"),
]


@pytest.mark.parametrize("n,hw,inv,syms,ndev,split", HYBRID_CONFIGS)
def test_hybrid_bit_identical_to_streamed(n, hw, inv, syms, ndev, split,
                                          rng):
    """Acceptance: hybrid y == streamed y to the BIT for every split —
    mixed, all-recompute (only the receive layout streams), and
    all-stream (the degenerate split equal to the pure tier)."""
    if _ndev() < ndev:
        pytest.skip(f"needs {ndev} devices")
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    if not op.effective_is_real:
        x = x.astype(np.complex128)
    es = DistributedEngine(op, n_devices=ndev, mode="streamed",
                           batch_size=64)
    eh = DistributedEngine(op, n_devices=ndev, mode="hybrid",
                           batch_size=64, hybrid_split=split)
    ys = np.asarray(es.matvec(es.to_hashed(x)))
    yh = np.asarray(eh.matvec(eh.to_hashed(x)))
    np.testing.assert_array_equal(ys, yh)
    # the partial-term plan carries fewer bytes than the full streamed
    # (same-tier) plan whenever terms recompute
    if split != "all-stream":
        assert eh.hybrid_stream_fraction < 1.0
    np.testing.assert_allclose(eh.from_hashed(yh), op.matvec_host(x),
                               atol=ATOL, rtol=RTOL)


@needs_8
def test_hybrid_batch_bit_identical(rng):
    """k=3 (one column group) and k=6 (two re-streamed groups) batches
    equal the streamed batches bit-for-bit."""
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    n = op.basis.number_states
    es = DistributedEngine(op, n_devices=8, mode="streamed")
    eh = DistributedEngine(op, n_devices=8, mode="hybrid",
                           hybrid_split="stream:1,3")
    for k in (3, 6):
        X = rng.random((n, k)) - 0.5
        np.testing.assert_array_equal(
            np.asarray(es.matvec(es.to_hashed(X))),
            np.asarray(eh.matvec(eh.to_hashed(X))))


@needs_4
def test_hybrid_pipelined_bit_identical(rng):
    """The PR 10 pipeline carries the hybrid chunk program at every
    depth: multichunk hybrid applies at depth 2 equal the sequential
    hybrid (and streamed) applies bit-for-bit, on a 4-shard AND a
    single-device mesh."""
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    for ndev, bs in ((4, 16), (1, 32)):
        es = DistributedEngine(op, n_devices=ndev, mode="streamed",
                               batch_size=bs)
        ys = np.asarray(es.matvec(es.to_hashed(x)))
        for depth in (0, 2):
            eh = DistributedEngine(op, n_devices=ndev, mode="hybrid",
                                   batch_size=bs,
                                   hybrid_split="stream:1,2,3",
                                   pipeline_depth=depth)
            assert eh._plan_nchunks_v > 1
            assert eh.pipeline_depth == depth
            np.testing.assert_array_equal(
                ys, np.asarray(eh.matvec(eh.to_hashed(x))))


@needs_4
def test_hybrid_single_chunk_auto_pipeline_sequential():
    """The PR 10 ``choose_pipeline_depth`` contract holds for the new
    mode: a single-chunk hybrid plan resolves ``pipeline_depth=auto`` to
    the sequential schedule (0), exactly like streamed."""
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    eh = DistributedEngine(op, n_devices=4, mode="hybrid",
                           hybrid_split="all-stream",
                           pipeline_depth="auto")
    assert eh._plan_nchunks_v == 1
    assert eh.pipeline_depth == 0


@needs_4
def test_hybrid_auto_split_is_priced(rng, monkeypatch):
    """The ``auto`` policy streams or recomputes per the calibrated
    rates: a flop-rich calibration prices every term's recompute under
    its stream cost (all-recompute), a flop-starved one the reverse
    (all-stream) — and the two splits carry DIFFERENT fingerprints (the
    rates are part of the v4 content hash)."""
    from distributed_matvec_tpu.obs import roofline as R

    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    base = {"exchange_bytes_per_s": 4e9, "backend": "cpu",
            "source": "test"}

    def eng_with(cal):
        monkeypatch.setattr(R, "resolve_calibration",
                            lambda *a, **k: dict(base, **cal))
        return DistributedEngine(op, n_devices=4, mode="hybrid",
                                 batch_size=64, hybrid_split="auto")

    fast_flops = eng_with({"flops_per_s": 1e15, "gather_rows_per_s": 1e6,
                           "h2d_bytes_per_s": 1e6})
    assert fast_flops.hybrid_stream_fraction == 0.0
    slow_flops = eng_with({"flops_per_s": 1e3, "gather_rows_per_s": 1e12,
                           "h2d_bytes_per_s": 1e12})
    assert slow_flops.hybrid_stream_fraction == 1.0
    assert fast_flops._structure_fingerprint() \
        != slow_flops._structure_fingerprint()
    # both priced splits stay bit-identical to streamed
    es = DistributedEngine(op, n_devices=4, mode="streamed",
                           batch_size=64)
    ys = np.asarray(es.matvec(es.to_hashed(x)))
    for eng in (fast_flops, slow_flops):
        np.testing.assert_array_equal(
            ys, np.asarray(eng.matvec(eng.to_hashed(x))))


@needs_4
def test_hybrid_split_fingerprint_cache(tmp_path, rng, monkeypatch):
    """The v4 fingerprint contract on the artifact cache: same split
    restores warm (bit-identically); a CHANGED ``hybrid_split`` misses
    (never misreads a partial-term plan); streamed and hybrid plans
    never cross-restore."""
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))

    e1 = DistributedEngine(op, n_devices=4, mode="hybrid", batch_size=64,
                           hybrid_split="stream:0,2,5")
    assert not e1.structure_restored
    y1 = np.asarray(e1.matvec(e1.to_hashed(x)))
    e2 = DistributedEngine(op, n_devices=4, mode="hybrid", batch_size=64,
                           hybrid_split="stream:0,2,5")
    assert e2.structure_restored
    assert np.array_equal(e2._hybrid_mask, e1._hybrid_mask)
    np.testing.assert_array_equal(
        y1, np.asarray(e2.matvec(e2.to_hashed(x))))

    e3 = DistributedEngine(op, n_devices=4, mode="hybrid", batch_size=64,
                           hybrid_split="stream:0,2")
    assert not e3.structure_restored, "changed split must miss"
    np.testing.assert_array_equal(
        y1, np.asarray(e3.matvec(e3.to_hashed(x))))

    es = DistributedEngine(op, n_devices=4, mode="streamed",
                           batch_size=64)
    assert not es.structure_restored, "streamed must not read hybrid"
    eh = DistributedEngine(op, n_devices=4, mode="hybrid", batch_size=64,
                           hybrid_split="stream:0,2,5")
    assert eh.structure_restored     # its own sidecar is still warm


@needs_4
def test_hybrid_v3_era_sidecar_misses_and_rebuilds(tmp_path, rng,
                                                   monkeypatch):
    """A v3-era (pure streamed) sidecar at the SAME explicit cache path
    never restores into a hybrid engine: the v4 fingerprint (mode +
    split token) misses, the engine rebuilds, and the answer is still
    bit-identical to streamed."""
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "off")
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    cache = str(tmp_path / "plan_cache.h5")
    es = DistributedEngine(op, n_devices=4, mode="streamed",
                           batch_size=64, structure_cache=cache)
    sidecar = es._stream_sidecar(cache)
    assert os.path.exists(sidecar), "streamed sidecar not written"
    ys = np.asarray(es.matvec(es.to_hashed(x)))
    eh = DistributedEngine(op, n_devices=4, mode="hybrid", batch_size=64,
                           hybrid_split="stream:0,2,5",
                           structure_cache=cache)
    assert not eh.structure_restored, \
        "hybrid engine restored a v3-era streamed sidecar"
    np.testing.assert_array_equal(
        ys, np.asarray(eh.matvec(eh.to_hashed(x))))


@needs_4
def test_hybrid_corrupt_chunk_heals_bit_identically(tmp_path, rng,
                                                    monkeypatch):
    """PR 6's per-chunk CRC heal through the hybrid codec: a
    checksum-corrupt streamed chunk of a DISK-tier hybrid plan rebuilds
    from structure mid-apply — re-encoded through the SAME term mask, so
    the healed apply is bit-identical."""
    import h5py

    from distributed_matvec_tpu import obs

    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    e1 = DistributedEngine(op, n_devices=4, mode="hybrid", batch_size=64,
                           hybrid_split="stream:0,2,5")
    y1 = np.asarray(e1.matvec(e1.to_hashed(x)))
    update_config(stream_plan_ram_gb=0.0)
    try:
        e2 = DistributedEngine(op, n_devices=4, mode="hybrid",
                               batch_size=64, hybrid_split="stream:0,2,5")
        assert e2.structure_restored
        assert e2._plan_chunks is None and e2._plan_disk
        path = next(iter(e2._plan_disk.values()))
        with h5py.File(path, "r+") as f:
            g = f["engine_structure"]
            a = g["dest_0_1"][...]
            a.view(np.uint8)[0] ^= 0xFF
            g["dest_0_1"][...] = a
        obs.reset_all()
        try:
            y2 = np.asarray(e2.matvec(e2.to_hashed(x)))
            assert obs.events("plan_chunk_rebuilt"), "no rebuild event"
        finally:
            obs.reset_all()
        np.testing.assert_array_equal(y1, y2)
    finally:
        update_config(stream_plan_ram_gb=8.0)


@needs_4
def test_hybrid_phase_split_and_exactness(rng):
    """Hybrid applies split ``compute`` into ``compute_decode`` /
    ``compute_recompute`` (the roofline prices each at its own
    resource), with the per-phase structural counts still summing to the
    event totals exactly."""
    from distributed_matvec_tpu import obs
    from distributed_matvec_tpu.obs import roofline as R

    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    obs.reset_all()
    try:
        eh = DistributedEngine(op, n_devices=4, mode="hybrid",
                               batch_size=64, hybrid_split="stream:0,2,5")
        xh = eh.to_hashed(x)
        for _ in range(3):
            eh.matvec(xh)
        evs = obs.events("apply_phases")
        ev = evs[-1]
        for f in ("bytes", "gathers", "flops"):
            assert sum(p[f] for p in ev["phases"].values()) \
                == ev[f + "_total"], f
        assert ev["phases"]["plan_h2d"]["bytes"] == eh.plan_bytes
        assert ev["phases"]["exchange"]["bytes"] == eh._exchange_nbytes(xh)
        assert ev["phases"]["compute_recompute"]["flops"] > 0
        assert ev["phases"]["compute_decode"]["gathers"] > 0
        rep = R.roofline_report(evs)
        assert "distributed/hybrid" in rep["groups"]
        # report walls are rounded to 4 decimals, so reconciliation is
        # rounding-bounded — the same tolerance test_phases.py asserts
        assert R.reconcile_error(rep) < 1e-3
    finally:
        obs.reset_all()


@needs_4
def test_hybrid_plan_stream_event_and_refusals(rng):
    """The plan_stream event carries the split's identity card
    (stream_term_fraction etc.), the off tier maps to the compacted
    lossless encoding, bad split strings raise, and the outer-trace
    solver refusal covers the new mode."""
    from distributed_matvec_tpu import obs
    from distributed_matvec_tpu.solve import lanczos

    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    obs.reset_all()
    try:
        eh = DistributedEngine(op, n_devices=4, mode="hybrid",
                               hybrid_split="stream:1,3")
        ps = [e for e in obs.events("plan_stream")
              if e.get("mode") == "hybrid"]
        assert ps and ps[-1]["hybrid_split"] == "stream:1,3"
        assert 0.0 < ps[-1]["stream_term_fraction"] < 1.0
        assert eh._codec.spec["tier"] == "lossless"   # off -> compacted
        assert eh._codec.spec["hybrid"] is True
        with pytest.raises(NotImplementedError):
            eh.bound_matvec()
        with pytest.raises(ValueError, match="lanczos_block"):
            lanczos(eh.matvec, v0=eh.random_hashed(seed=1), k=1)
    finally:
        obs.reset_all()
    with pytest.raises(ValueError, match="hybrid split"):
        DistributedEngine(op, n_devices=4, mode="hybrid",
                          hybrid_split="bogus")
    with pytest.raises(ValueError, match="outside"):
        DistributedEngine(op, n_devices=4, mode="hybrid",
                          hybrid_split="stream:9999")


def test_codec_term_mask_unit():
    """PlanCodec term-mask contract: masked build stores only the
    streamed terms' entries while the capacity trim still covers ALL
    live entries; an off-tier masked build is refused; the mask
    round-trips through the spec JSON."""
    from distributed_matvec_tpu.ops import plan_codec as PC

    B, T, D, cap = 8, 4, 2, 16
    rng = np.random.default_rng(5)
    coeff = rng.random((B, T)) * (rng.random((B, T)) < 0.6)
    dest = np.full(B * T, D * cap, np.int32)
    live = np.nonzero(coeff.reshape(-1))[0]
    # simple bucket layout: entries alternate buckets, contiguous ranks
    for j, i in enumerate(live):
        b = j % D
        dest[i] = b * cap + (j // D)
    pc = {"dest": dest, "coeff": coeff,
          "ridx": np.arange(D * cap, dtype=np.int32) % B,
          "rok": np.ones(D * cap, bool)}
    mask = np.array([True, False, True, False])
    codec = PC.PlanCodec.build(
        "lossless", [{0: pc}], n_dest=B * T, cap_build=cap, n_devices=D,
        shard_size=B, cshape=(B, T), ckind="real", term_mask=mask)
    full = PC.PlanCodec.build(
        "lossless", [{0: pc}], n_dest=B * T, cap_build=cap, n_devices=D,
        shard_size=B, cshape=(B, T), ckind="real")
    # trim identical (all live entries), storage census masked-smaller
    assert codec.spec["cap_eff"] == full.spec["cap_eff"]
    assert codec.spec["n_live"] <= full.spec["n_live"]
    assert codec.spec["stream_terms"] == [0, 2]
    np.testing.assert_array_equal(codec.term_mask(), mask)
    rt = PC.PlanCodec.from_spec_json(codec.spec_json())
    np.testing.assert_array_equal(rt.term_mask(), mask)
    # the compacted record holds ONLY masked-term entries
    cp = codec.compact_raw(pc)
    kept = cp["coeff"][cp["coeff"] != 0]
    want = coeff[:, mask].reshape(-1)
    np.testing.assert_array_equal(np.sort(kept),
                                  np.sort(want[want != 0]))
    with pytest.raises(ValueError, match="compacted tier"):
        PC.PlanCodec.build(
            "off", [{0: pc}], n_dest=B * T, cap_build=cap, n_devices=D,
            shard_size=B, cshape=(B, T), ckind="real", term_mask=mask)


def test_local_engine_hybrid_pointer():
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    with pytest.raises(ValueError, match="DistributedEngine"):
        LocalEngine(op, mode="hybrid")


def test_two_process_hybrid(tmp_path):
    """A REAL 2-process run (multihost worker, DMT_MH_HYBRID leg):
    rank-local streamed + hybrid engines with a pinned mixed split —
    bit-identity, correctness, and partial-plan-smaller-than-streamed
    asserted on BOTH ranks of a real jax.distributed job."""
    import re
    import socket
    import subprocess
    import sys as _sys

    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["DMT_MH_HYBRID"] = "stream:0,1,2,3"
    env["DMT_OBS_DIR"] = str(tmp_path / "run")
    procs = [subprocess.Popen(
        [_sys.executable, worker, str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]
        m = re.search(rf"\[p{pid}\] HYBRID_PLAN_BYTES (\d+) (\d+)", out)
        assert m, out[-2000:]
        assert int(m.group(1)) < int(m.group(2))
