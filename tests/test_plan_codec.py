"""Compressed plan streams (ops/plan_codec.py + the streamed engine tiers).

Codec invariants: the bitpack round-trips exactly on host and device; the
lossless tier decodes to the raw plan bit-for-bit (so the apply stays
bit-identical to fused); the quantized tiers stay inside their documented
bounds with f64 accumulation; the sidecar carries the codec (v3
fingerprint — older-format files miss and rebuild, never misread); and a
corrupt compressed chunk heals through the PR 6 ``plan_chunk_rebuilt``
path bit-consistently.
"""

import os

import jax
import numpy as np
import pytest

from distributed_matvec_tpu.ops import plan_codec as PC
from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.utils.config import update_config

from test_operator import build_heisenberg


def _ndev() -> int:
    return len(jax.devices())


needs_4 = pytest.mark.skipif("_ndev() < 4", reason="needs 4 virtual devices")


@pytest.fixture
def tier(request):
    """Set a stream_compress tier for one test, restoring off after."""
    update_config(stream_compress=request.param)
    yield request.param
    update_config(stream_compress="off")


# -- bitpacking -------------------------------------------------------------


@pytest.mark.parametrize("width", [1, 3, 8, 13, 17, 24, 31, 32])
def test_pack_bits_roundtrip(width, rng):
    n = 517
    v = rng.integers(0, (1 << width) - 1, n, endpoint=True,
                     dtype=np.uint64)
    packed = PC.pack_bits(v, width)
    assert packed.dtype == np.uint32
    assert packed.size == PC.packed_words(n, width)
    assert np.array_equal(PC.unpack_bits_np(packed, n, width), v)
    # device unpack agrees with the host reference
    import jax.numpy as jnp
    dev = jax.jit(lambda p: PC.unpack_bits(p, n, width))(jnp.asarray(packed))
    assert np.array_equal(np.asarray(dev).astype(np.uint64), v)


def test_pack_bits_rejects_overflow():
    with pytest.raises(ValueError, match="does not fit"):
        PC.pack_bits(np.array([9], np.uint64), 3)


# -- chunk round trip -------------------------------------------------------


def _chunk(rng, B=24, T=5, n_recv=64, M=48, ckind="real", values=None):
    if values is None:
        values = np.array([0.0, 0.5, -0.5, 1.25, -2.0])
    cf = rng.choice(values, (B, T))
    if ckind == "pair":
        cf = np.stack([cf, rng.choice(values, (B, T))], axis=-1)
    elif ckind == "complex":
        cf = cf + 1j * rng.choice(values, (B, T))
    return {"dest": rng.integers(0, n_recv, B * T,
                                 endpoint=True).astype(np.int32),
            "coeff": cf,
            "ridx": rng.integers(0, M, n_recv).astype(np.int32),
            "rok": rng.integers(0, 2, n_recv).astype(bool)}


@pytest.mark.parametrize("ckind", ["real", "pair", "complex"])
@pytest.mark.parametrize("tier_name", ["off", "lossless", "f32", "bf16"])
def test_codec_chunk_roundtrip(tier_name, ckind, rng):
    B, T, n_recv, M = 24, 5, 64, 48
    cshape = (B, T) + ((2,) if ckind == "pair" else ())
    pc = _chunk(rng, B, T, n_recv, M, ckind)
    codec = PC.PlanCodec.build(tier_name, [{0: pc}], n_dest=B * T,
                               cap_build=n_recv, n_devices=1,
                               shard_size=M, cshape=cshape, ckind=ckind)
    enc = codec.encode_chunk(pc, 0)
    dec = codec.decode_chunk_host(enc, 0)
    if tier_name == "off":
        for k in ("dest", "ridx", "rok"):
            assert np.array_equal(np.asarray(dec[k]), np.asarray(pc[k])), k
        assert np.array_equal(np.asarray(dec["coeff"]), pc["coeff"])
        return
    # compressed tiers round-trip the COMPACT form (live entries +
    # trimmed receive layout); compact_raw is the oracle
    ref = codec.compact_raw(pc)
    for k in ("dest", "row", "ridx", "rok"):
        assert np.array_equal(np.asarray(dec[k]), np.asarray(ref[k])), k
    if tier_name == "lossless":
        assert np.array_equal(np.asarray(dec["coeff"]), ref["coeff"])
    else:
        rtol = 1e-6 if tier_name == "f32" else 1e-2
        np.testing.assert_allclose(dec["coeff"], ref["coeff"], rtol=rtol,
                                   atol=rtol)
    assert codec.spec["coeff"] == "dict"
    assert PC.PlanCodec.encoded_bytes(enc) * 2 < codec.raw_chunk_bytes()


def test_codec_raw_fallback_when_dict_overflows(rng):
    """Continuous coefficients blow the dictionary: the codec degrades to
    raw (quantized) compacted coefficient vectors, still with packed
    indices."""
    B, T, n_recv, M = 16, 4, 32, 32
    pc = _chunk(rng, B, T, n_recv, M, values=rng.standard_normal(B * T))
    codec = PC.PlanCodec.build("f32", [{0: pc}], n_dest=B * T,
                               cap_build=n_recv, n_devices=1,
                               shard_size=M, cshape=(B, T),
                               ckind="real", dict_max=8)
    assert codec.spec["coeff"] == "raw"
    enc = codec.encode_chunk(pc, 0)
    assert enc["coeff"].dtype == np.float32
    dec = codec.decode_chunk_host(enc, 0)
    ref = codec.compact_raw(pc)
    np.testing.assert_allclose(dec["coeff"], ref["coeff"], rtol=1e-6)
    assert np.array_equal(dec["dest"], ref["dest"])
    assert np.array_equal(dec["row"], ref["row"])


def test_codec_compaction_and_trim(rng):
    """The compressed spec reflects the measured plan: n_live covers the
    live census (padded to 8), cap_eff equals the max bucket fill, and
    the compact form's row/dest agree with a hand computation."""
    B, T, cap, M = 16, 4, 40, 32
    pc = _chunk(rng, B, T, cap, M)
    codec = PC.PlanCodec.build("lossless", [{0: pc}], n_dest=B * T,
                               cap_build=cap, n_devices=1,
                               shard_size=M, cshape=(B, T), ckind="real")
    dest_all = np.asarray(pc["dest"], np.int64)
    live = (pc["coeff"].reshape(-1) != 0) & (dest_all < cap)
    n_live = int(live.sum())
    assert n_live <= codec.spec["n_live"] <= n_live + 8
    assert codec.spec["cap_eff"] == max(
        int((dest_all[live] % cap).max()) + 1, 1)
    cp = codec.compact_raw(pc)
    rows = np.nonzero(live)[0] // T
    assert np.array_equal(cp["row"][:n_live], rows)
    assert np.all(cp["dest"][n_live:] == codec.spec["n_recv"])
    assert cp["ridx"].size == codec.spec["n_recv"]


def test_codec_spec_json_roundtrip(rng):
    pc = _chunk(rng)
    codec = PC.PlanCodec.build("lossless", [{0: pc}], n_dest=120,
                               cap_build=64, n_devices=1, shard_size=48,
                               cshape=(24, 5), ckind="real")
    restored = PC.PlanCodec.from_spec_json(codec.spec_json())
    assert restored.spec == codec.spec
    restored.set_dict(0, codec.dict_store(0))
    assert np.array_equal(restored.dicts[0], codec.dicts[0])
    # a restored codec re-encodes BIT-identically (the corrupt-chunk
    # rebuild contract: the healed chunk must match the stored CRC)
    e1, e2 = codec.encode_chunk(pc, 0), restored.encode_chunk(pc, 0)
    for k in e1:
        assert np.array_equal(e1[k], e2[k]), k


def test_codec_version_gate():
    with pytest.raises(ValueError, match="version"):
        PC.PlanCodec({"version": 99, "tier": "off"})


# -- engine tiers vs the fused truth ---------------------------------------


@needs_4
@pytest.mark.parametrize("tier", ["off", "lossless"], indirect=True)
def test_compressed_stream_bit_identical_to_fused(tier, rng):
    """off and lossless tiers reproduce fused to the BIT (single + k=3
    batch) on a |G|>1 symm config — lossless decodes exact f64 dictionary
    values, so nothing changes numerically."""
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    ef = DistributedEngine(op, n_devices=4, mode="fused", batch_size=64)
    es = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=64)
    yf = np.asarray(ef.matvec(ef.to_hashed(x)))
    ys = np.asarray(es.matvec(es.to_hashed(x)))
    np.testing.assert_array_equal(yf, ys)
    X3 = np.stack([x, -x, 0.5 * x], axis=1)
    np.testing.assert_array_equal(
        np.asarray(ef.matvec(ef.to_hashed(X3))),
        np.asarray(es.matvec(es.to_hashed(X3))))
    if tier == "lossless":
        assert es._codec.spec["coeff"] == "dict"
        assert es.plan_bytes * 2 < es.plan_bytes_raw
    else:
        # the satellite: rok is bitpacked even uncompressed
        assert es._plan_chunks[0][0]["rok"].dtype == np.uint32


@needs_4
@pytest.mark.parametrize("tier", ["f32", "bf16"], indirect=True)
def test_quantized_tiers_within_documented_bounds(tier, rng):
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    ef = DistributedEngine(op, n_devices=4, mode="fused", batch_size=64)
    es = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=64)
    yf = np.asarray(ef.matvec(ef.to_hashed(x)))
    ys = np.asarray(es.matvec(es.to_hashed(x)))
    rel = np.max(np.abs(ys - yf)) / np.max(np.abs(yf))
    assert rel <= (1e-6 if tier == "f32" else 1e-2), (tier, rel)


@needs_4
@pytest.mark.parametrize("tier", ["lossless"], indirect=True)
def test_compressed_complex_sector(tier, rng):
    """Native-c128 momentum sector: complex dictionary, exact decode."""
    op = build_heisenberg(10, 5, None, [([*range(1, 10), 0], 1)])
    op.basis.build()
    x = (rng.random(op.basis.number_states) - 0.5).astype(np.complex128)
    ef = DistributedEngine(op, n_devices=4, mode="fused", batch_size=64)
    es = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=64)
    np.testing.assert_array_equal(
        np.asarray(ef.matvec(ef.to_hashed(x))),
        np.asarray(es.matvec(es.to_hashed(x))))


@needs_4
@pytest.mark.parametrize("tier", ["lossless"], indirect=True)
def test_pallas_decode_kernel_matches_xla(tier, rng):
    """The fused decode+gather+multiply+scatter Pallas kernel (interpret
    mode on CPU) is bit-identical to the XLA decode path."""
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    e_x = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=64)
    y_x = np.asarray(e_x.matvec(e_x.to_hashed(x)))
    update_config(stream_kernel="pallas")
    try:
        e_p = DistributedEngine(op, n_devices=4, mode="streamed",
                                batch_size=64)
        y_p = np.asarray(e_p.matvec(e_p.to_hashed(x)))
    finally:
        update_config(stream_kernel="auto")
    np.testing.assert_array_equal(y_x, y_p)


# -- sidecar: v3 fingerprint, compressed round trip, corrupt chunk ---------


@needs_4
@pytest.mark.parametrize("tier", ["lossless"], indirect=True)
def test_compressed_sidecar_roundtrip_and_disk_tier(tier, tmp_path, rng,
                                                    monkeypatch):
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    e1 = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=64)
    assert not e1.structure_restored
    y1 = np.asarray(e1.matvec(e1.to_hashed(x)))
    e2 = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=64)
    assert e2.structure_restored
    assert e2._codec.spec == e1._codec.spec
    np.testing.assert_array_equal(
        y1, np.asarray(e2.matvec(e2.to_hashed(x))))
    # disk tier reads the ENCODED chunks back per apply
    update_config(stream_plan_ram_gb=0.0)
    try:
        e3 = DistributedEngine(op, n_devices=4, mode="streamed",
                               batch_size=64)
        assert e3.structure_restored
        assert e3._plan_chunks is None and e3._plan_disk
        np.testing.assert_array_equal(
            y1, np.asarray(e3.matvec(e3.to_hashed(x))))
    finally:
        update_config(stream_plan_ram_gb=8.0)


@needs_4
def test_sidecar_fingerprint_tier_and_format_miss(tmp_path, rng,
                                                  monkeypatch):
    """The v3 fingerprint bakes in the compress tier and codec version:
    an off-tier sidecar never restores into a lossless engine (and vice
    versa), and a v2-era fingerprint (no codec tag) cannot match — the
    miss-and-rebuild path, never a misread."""
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    e_off = DistributedEngine(op, n_devices=4, mode="streamed",
                              batch_size=64)
    assert not e_off.structure_restored
    fp_off = e_off._structure_fingerprint()
    update_config(stream_compress="lossless")
    try:
        e_l = DistributedEngine(op, n_devices=4, mode="streamed",
                                batch_size=64)
        # the off-tier sidecar exists but must MISS for the lossless tier
        assert not e_l.structure_restored
        assert e_l._structure_fingerprint() != fp_off
        np.testing.assert_array_equal(
            np.asarray(e_off.matvec(e_off.to_hashed(x))),
            np.asarray(e_l.matvec(e_l.to_hashed(x))))
        # and a second lossless engine restores its own sidecar
        e_l2 = DistributedEngine(op, n_devices=4, mode="streamed",
                                 batch_size=64)
        assert e_l2.structure_restored
    finally:
        update_config(stream_compress="off")
    # a sidecar whose fingerprint predates v3 (simulated stale write at
    # the SAME path) is ignored: the engine rebuilds instead of reading
    # the old format
    import glob

    import h5py
    side = glob.glob(str(tmp_path / "art" / "structure" / "**"
                         / "*.stream.h5"), recursive=True)
    assert side
    for s in side:
        with h5py.File(s, "r+") as f:
            f["engine_structure"].attrs["fingerprint"] = "v2-era-stale"
    e_new = DistributedEngine(op, n_devices=4, mode="streamed",
                              batch_size=64)
    assert not e_new.structure_restored
    np.testing.assert_array_equal(
        np.asarray(e_off.matvec(e_off.to_hashed(x))),
        np.asarray(e_new.matvec(e_new.to_hashed(x))))


@needs_4
@pytest.mark.parametrize("tier", ["lossless"], indirect=True)
def test_corrupt_compressed_chunk_rebuilds_bit_consistently(
        tier, tmp_path, rng, monkeypatch):
    """A checksum-corrupt ENCODED chunk on the disk tier heals through the
    PR 6 ``plan_chunk_rebuilt`` path: the chunk re-resolves from structure,
    re-encodes with the restored codec, and the apply stays bit-identical
    to the uncorrupted plan."""
    import gc
    import glob

    import h5py

    from distributed_matvec_tpu import obs

    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    e1 = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=64)
    y1 = np.asarray(e1.matvec(e1.to_hashed(x)))
    del e1
    gc.collect()          # close any lazily-opened sidecar handles
    side = glob.glob(str(tmp_path / "art" / "structure" / "**"
                         / "*.stream.h5"), recursive=True)
    assert side
    with h5py.File(side[0], "r+") as f:
        g = f["engine_structure"]
        key = sorted(k for k in g if k.startswith("coeff_"))[0]
        a = g[key][...]
        flat = a.reshape(-1)
        flat[0] ^= np.asarray(1, a.dtype)     # encoded arrays are integral
        del g[key]
        g.create_dataset(key, data=a)
    update_config(stream_plan_ram_gb=0.0)
    obs.reset_all()
    try:
        e2 = DistributedEngine(op, n_devices=4, mode="streamed",
                               batch_size=64)
        assert e2.structure_restored and e2._plan_disk
        y2 = np.asarray(e2.matvec(e2.to_hashed(x)))
        np.testing.assert_array_equal(y1, y2)
        assert obs.events("plan_chunk_rebuilt"), \
            "corrupt chunk healed without the rebuild path"
    finally:
        update_config(stream_plan_ram_gb=8.0)
        obs.reset_all()


# -- observability / planner plumbing --------------------------------------


@needs_4
@pytest.mark.parametrize("tier", ["lossless"], indirect=True)
def test_phase_bytes_and_ledger_report_encoded(tier, rng):
    """The measurement plane reports ENCODED bytes end to end: the
    apply_phases plan_h2d bytes, the bytes_h2d counter, the plan_stream
    event, and the memory-ledger context the capacity planner reads."""
    from distributed_matvec_tpu import obs

    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    obs.reset_all()
    try:
        es = DistributedEngine(op, n_devices=4, mode="streamed",
                               batch_size=64)
        assert es.plan_bytes < es.plan_bytes_raw
        ps = obs.events("plan_stream")[-1]
        assert ps["plan_bytes"] == es.plan_bytes
        assert ps["plan_bytes_raw"] == es.plan_bytes_raw
        assert ps["compress"] == "lossless"
        assert ps["compress_ratio"] == pytest.approx(
            es.plan_bytes_raw / es.plan_bytes, rel=1e-3)
        led = [e for e in obs.events("memory_ledger")
               if e.get("mode") == "streamed"][-1]
        assert led["plan_bytes"] == es.plan_bytes
        assert led["plan_bytes_raw"] == es.plan_bytes_raw
        assert led["stream_compress"] == "lossless"
        c0 = obs.snapshot()["counters"].get(
            "bytes_h2d{path=plan_stream}", 0)
        x = rng.random(op.basis.number_states) - 0.5
        es.matvec(es.to_hashed(x))
        c1 = obs.snapshot()["counters"]["bytes_h2d{path=plan_stream}"]
        assert c1 - c0 == es.plan_bytes     # the stream carries encoded
        pev = [e for e in obs.events("apply_phases")
               if e.get("mode") == "streamed"][-1]
        assert pev["phases"]["plan_h2d"]["bytes"] == es.plan_bytes
    finally:
        obs.reset_all()


def test_capacity_models_compressed_settings():
    import importlib.util
    import os as _os
    spec = importlib.util.spec_from_file_location(
        "capacity", _os.path.join(_os.path.dirname(__file__), "..",
                                  "tools", "capacity.py"))
    cap = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cap)
    off = cap.stream_plan_bytes_per_row(36, False, "off")
    loss = cap.stream_plan_bytes_per_row(36, False, "lossless")
    f32 = cap.stream_plan_bytes_per_row(36, False, "f32")
    bf16 = cap.stream_plan_bytes_per_row(36, False, "bf16")
    assert off > f32 > loss and off > bf16
    assert off / loss >= 2.0
    rep = cap.plan(63_000_000, 36, 24, False, 16.0, 8, 3, 1,
                   stream_compress="lossless")
    m = rep["modes"]["streamed"]
    assert m["stream_compress"] == "lossless"
    by = m["host_plan_bytes_per_row_by_compress"]
    assert set(by) == {"off", "lossless", "f32", "bf16"}
    assert m["host_plan_bytes_per_row"] == by["lossless"]
    # measured calibration anchors the recorded tier and scales the rest
    measured = {"mode": "streamed", "n_padded": 1000, "plan_bytes": 100_000,
                "plan_bytes_raw": 420_000, "stream_compress": "lossless"}
    rep2 = cap.plan(63_000_000, 36, 24, False, 16.0, 8, 3, 1,
                    measured=measured, stream_compress="lossless")
    by2 = rep2["modes"]["streamed"]["host_plan_bytes_per_row_by_compress"]
    assert by2["lossless"] == pytest.approx(100.0)
    assert by2["off"] == pytest.approx(420.0)
