"""Production telemetry plane (ISSUE 17): OpenMetrics export parity,
SLO burn-rate evaluation + alert transitions, the crash flight recorder,
and the ``obs_report slo`` / ``postmortem`` readers.

The suite-wide conftest strips ``DMT_OBS_DIR``/``DMT_OBS`` from the
environment, so the layer runs enabled + in-memory by default; tests
that need a sink or the off state set it themselves and reset around.
"""

import importlib.util
import json
import os
import sys
import urllib.request

import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.obs.slo import SloSpec, default_slos, evaluate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def clean_obs():
    obs.reset_all()
    yield
    obs.reset_all()


@pytest.fixture
def obs_off(monkeypatch):
    monkeypatch.setenv("DMT_OBS", "off")


def _fill_registry():
    obs.counter("slo_test_total").inc(3)
    obs.counter("slo_test_labeled", engine="local").inc()
    obs.gauge("slo_test_gauge").set(0.1 + 0.2)      # not repr-trivial
    h = obs.histogram("slo_test_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    return obs.snapshot()


# ---------------------------------------------------------------------------
# OpenMetrics export parity


def test_openmetrics_render_parse_roundtrip(clean_obs):
    """parse(render(snapshot)) == snapshot EXACTLY — repr floats survive
    the text round trip, histograms keep buckets/sum/count."""
    snap = _fill_registry()
    text = obs.render_openmetrics(snap)
    assert "# EOF" in text
    assert obs.parse_openmetrics(text) == snap


def test_openmetrics_merge_disjoint_ranks(clean_obs):
    snap = _fill_registry()
    r0 = obs.render_openmetrics(snap, extra_labels={"rank": "0"})
    r1 = obs.render_openmetrics(snap, extra_labels={"rank": "1"})
    merged = obs.merge_openmetrics([r0, r1])
    assert merged.count("# EOF") == 1
    assert 'rank="0"' in merged and 'rank="1"' in merged


def test_http_scrape_equals_registry(clean_obs):
    """A REAL ephemeral-port scrape agrees exactly with the registry."""
    snap = _fill_registry()
    server = obs.start_exporter(port=0)
    try:
        assert server is not None and server.port > 0
        url = f"http://127.0.0.1:{server.port}"
        body = urllib.request.urlopen(f"{url}/metrics",
                                      timeout=10).read().decode()
        assert obs.parse_openmetrics(body) == snap
        health = json.loads(urllib.request.urlopen(
            f"{url}/healthz", timeout=10).read().decode())
        assert health["status"] == "ok"
        assert health["rank"] == 0
    finally:
        obs.stop_exporter()


def test_textfile_roundtrip(clean_obs, monkeypatch, tmp_path):
    monkeypatch.setenv("DMT_OBS_DIR", str(tmp_path / "run"))
    snap = _fill_registry()
    path = obs.write_textfile()
    assert path and path.endswith("metrics.prom")
    with open(path) as f:
        assert obs.parse_openmetrics(f.read()) == snap


# ---------------------------------------------------------------------------
# SLO evaluation (pure: synthetic event lists)


def _apply_events(values, t0=1000.0, dt=1.0):
    return [{"kind": "matvec_apply", "ts": t0 + i * dt, "wall_ms": v}
            for i, v in enumerate(values)]


def test_slo_threshold_pinned_target_fires():
    spec = SloSpec("steady_apply_ms", kind="matvec_apply", field="wall_ms",
                   target=10.0)
    # 3/10 samples violate: frac 0.3 / budget 0.01 = burn 30 > both
    # window thresholds (14.4x / 6x) => firing
    st, = evaluate(_apply_events([1.0] * 7 + [100.0] * 3), [spec])
    assert st["state"] == "firing"
    assert all(w["burn"] > w["max_burn"] for w in st["windows"])
    # 1/100 violating stays inside the objective budget
    st, = evaluate(_apply_events([1.0] * 99 + [100.0]), [spec])
    assert st["state"] == "ok"


def test_slo_auto_baseline_from_head():
    """target=None self-baselines: median of the earliest quartile x
    slack — a 50x late-run regression fires without any pinned number."""
    spec = SloSpec("steady_apply_ms", kind="matvec_apply", field="wall_ms")
    st, = evaluate(_apply_events([10.0] * 20 + [500.0] * 10), [spec])
    assert st["state"] == "firing"
    assert st["target"] == pytest.approx(40.0)      # median 10 * slack 4
    st, = evaluate(_apply_events([10.0] * 30), [spec])
    assert st["state"] == "ok"


def test_slo_multiwindow_requires_every_window():
    """A burst that only pollutes the short window must NOT page: the
    long window's burn stays under its threshold."""
    spec = SloSpec("steady_apply_ms", kind="matvec_apply", field="wall_ms",
                   target=10.0, windows=((60.0, 10.0), (3600.0, 30.0)))
    # 3000 old-good + 10 recent-bad: short window 100% bad (burn 100),
    # long window frac 10/3010 => burn ~0.33 < 30
    events = _apply_events([1.0] * 3000, t0=0.0, dt=1.0) + \
        _apply_events([100.0] * 10, t0=3005.0, dt=1.0)
    st, = evaluate(events, [spec])
    assert st["state"] == "ok"
    assert st["windows"][0]["burn"] > st["windows"][0]["max_burn"]
    assert st["windows"][1]["burn"] < st["windows"][1]["max_burn"]


def test_slo_no_data_and_count_modes():
    statuses = {s["name"]: s for s in evaluate([], default_slos())}
    assert statuses["steady_apply_ms"]["state"] == "no-data"
    assert statuses["faults_injected"]["state"] == "ok"   # zero events
    st = {s["name"]: s for s in evaluate(
        [{"kind": "fault_injected", "ts": 1.0, "site": "x"}],
        default_slos())}["faults_injected"]
    assert st["state"] == "firing"          # allowed/h = 0: any is too many
    assert st["worst_burn"] == float("inf")


def test_slo_rate_min_short_run_clamps_window():
    """The rate denominator clamps to the observed span: a 2-s CI drain
    at 6 solves must NOT grade as ~1/min against a 300-s window."""
    done = [{"kind": "job_event", "status": "done", "ts": 1000.0 + 0.4 * i,
             "latency_ms": 100.0} for i in range(6)]
    spec = SloSpec("serve_solves_per_min", kind="job_event",
                   where={"status": "done"}, mode="rate_min", target=60.0)
    st, = evaluate(done, [spec])
    assert st["state"] == "ok"              # ~180/min over the 2-s span
    # a genuinely slow drain still fires the floor
    slow = [{"kind": "job_event", "status": "done", "ts": 1000.0 + 30.0 * i,
             "latency_ms": 100.0} for i in range(6)]
    st, = evaluate(slow, [spec])
    assert st["state"] == "firing"          # 2.4/min < 60/min floor


def test_check_slos_alert_transitions(clean_obs):
    """ok->firing emits ONE critical slo_alert + bumps slo_alert_count;
    steady firing emits nothing; recovery emits state=clear."""
    spec = SloSpec("steady_apply_ms", kind="matvec_apply", field="wall_ms",
                   target=10.0)
    bad = _apply_events([100.0] * 10)
    obs.check_slos([spec], events=bad)
    obs.check_slos([spec], events=bad)      # steady: no second alert
    alerts = [e for e in obs.events() if e.get("kind") == "slo_alert"]
    assert len(alerts) == 1
    assert alerts[0]["state"] == "firing"
    assert alerts[0]["slo"] == "steady_apply_ms"
    assert alerts[0]["level"] == "critical"
    assert obs.snapshot()["counters"]["slo_alert_count"] == 1
    obs.check_slos([spec], events=_apply_events([1.0] * 10))
    alerts = [e for e in obs.events() if e.get("kind") == "slo_alert"]
    assert [a["state"] for a in alerts] == ["firing", "clear"]
    assert obs.snapshot()["counters"]["slo_alert_count"] == 1


# ---------------------------------------------------------------------------
# flight recorder


def _crash_site(run, monkeypatch):
    monkeypatch.setenv("DMT_OBS_DIR", str(run))
    obs.reset_all()
    obs.emit("engine_init", mode="ell")
    obs.counter("slo_test_total").inc()


def test_flight_dump_bundle_roundtrip(clean_obs, monkeypatch, tmp_path):
    _crash_site(tmp_path / "run", monkeypatch)
    with obs.span("lanczos", kind="solve"):
        with obs.span("apply", kind="apply", apply=7):
            path = obs.flight_dump("stall", exit_code=76,
                                   report={"stalled": [1]})
    assert path and os.path.basename(path).startswith("stall-")
    bundle = obs.read_bundle(path)
    assert bundle["reason"] == "stall" and bundle["exit_code"] == 76
    assert bundle["report"] == {"stalled": [1]}
    assert bundle["span_path"] == "lanczos>apply"
    assert bundle["span"]["kind"] == "apply"
    assert any(e.get("kind") == "engine_init" for e in bundle["events"])
    assert bundle["metrics"]["counters"]["slo_test_total"] == 1
    assert obs.verify_bundle(path)
    assert obs.list_bundles() == [path]
    # content address: the name IS the hash of the bytes
    digest = os.path.basename(path).split("-", 1)[1].split(".")[0]
    assert len(digest) == 16
    # once per reason; reset re-arms
    assert obs.flight_dump("stall") is None
    assert obs.flight_dump("oom", exit_code=1) is not None
    obs.reset_flight()
    assert obs.flight_dump("stall") is not None


def test_flight_bundle_tamper_detected(clean_obs, monkeypatch, tmp_path):
    _crash_site(tmp_path / "run", monkeypatch)
    path = obs.flight_dump("stall", exit_code=76)
    assert obs.verify_bundle(path)
    bundle = json.load(open(path))
    bundle["exit_code"] = 0                 # the cover-up
    with open(path, "w") as f:
        json.dump(bundle, f)
    assert not obs.verify_bundle(path)


def test_flight_dump_without_sink_is_none(clean_obs):
    assert obs.run_dir() is None
    assert obs.flight_dump("stall", exit_code=76) is None


# ---------------------------------------------------------------------------
# DMT_OBS=off: provable no-op


def test_obs_off_everything_inert(obs_off, tmp_path, monkeypatch):
    monkeypatch.setenv("DMT_OBS_DIR", str(tmp_path / "never"))
    assert not obs.obs_enabled()
    assert obs.start_exporter(port=0) is None
    assert obs.write_textfile() is None
    assert obs.flight_dump("stall", exit_code=76) is None
    assert obs.postmortem_dir() is None
    assert obs.check_slos() == []
    obs.emit("probe", x=1)
    assert obs.events() == []
    assert not os.path.exists(str(tmp_path / "never"))


# ---------------------------------------------------------------------------
# obs_report slo / postmortem readers


def _write_run(run, events):
    rank = os.path.join(run, "rank_0")
    os.makedirs(rank, exist_ok=True)
    with open(os.path.join(rank, "events.jsonl"), "w") as f:
        for i, e in enumerate(events):
            f.write(json.dumps({"seq": i, "rank": 0, **e}) + "\n")


def test_obs_report_slo_exit_codes(tmp_path):
    rep = _load_tool("obs_report")
    run = str(tmp_path / "run")
    _write_run(run, _apply_events([10.0] * 20 + [500.0] * 10))
    assert rep.main(["slo", run]) == 1              # auto-baseline burns
    assert rep.main(["slo", run, "--target", "steady_apply_ms=1000"]) == 0
    out = json.loads("".join(_capture_json(rep, ["slo", run, "--json"])))
    by = {s["name"]: s for s in out}
    assert by["steady_apply_ms"]["state"] == "firing"


def _capture_json(rep, argv):
    import contextlib
    import io
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rep.main(argv)
    return buf.getvalue()


def test_obs_report_postmortem(clean_obs, monkeypatch, tmp_path):
    rep = _load_tool("obs_report")
    run = str(tmp_path / "run")
    _crash_site(tmp_path / "run", monkeypatch)
    # no bundle yet: exit 2 (distinct from "bundle invalid")
    assert rep.main(["postmortem", run]) == 2
    with obs.span("lanczos", kind="solve"):
        path = obs.flight_dump("stall", exit_code=76,
                               report={"stalled": [1]})
    assert rep.main(["postmortem", run]) == 0
    entries = rep.scan_postmortems(run)
    assert len(entries) == 1 and entries[0]["valid"]
    assert entries[0]["bundle"]["span_path"] == "lanczos"
    with open(path, "a") as f:                      # torn write
        f.write("}")
    assert rep.main(["postmortem", run]) == 1


# ---------------------------------------------------------------------------
# the REAL 2-process export leg


def _free_port_pair():
    import socket
    for _ in range(20):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
        try:
            with socket.socket() as t:
                t.bind(("127.0.0.1", base + 1))
        except OSError:
            continue
        return base
    raise RuntimeError("no adjacent free port pair")


def test_multihost_export_two_ranks(tmp_path):
    """2-process run (multihost worker harness, export leg): each rank
    serves /metrics + /healthz on DMT_OBS_PORT + rank, both ranks scrape
    both endpoints and agree on ONE trace id, and rank 0's endpoint
    aggregates rank 1's textfile into one labeled document."""
    import socket
    import subprocess

    rep = _load_tool("obs_report")
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = s.getsockname()[1]
    base = _free_port_pair()
    run = tmp_path / "export_run"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["DMT_MH_EXPORT"] = "1"
    env["DMT_OBS_DIR"] = str(run)
    env["DMT_OBS_PORT"] = str(base)
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), "2", str(coord)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    tids = set()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]
        line = [ln for ln in out.splitlines()
                if ln.startswith(f"[p{pid}] EXPORT_TRACE_ID ")][0]
        tids.add(line.split()[-1])
    # one scraped trace id across both ranks, and it IS the run's id
    assert len(tids) == 1
    events = rep.load_events(str(run))
    assert {e.get("trace_id") for e in events} == tids
    # each rank left its textfile, parseable stand-alone
    for r in (0, 1):
        tf = run / f"rank_{r}" / "metrics.prom"
        assert tf.exists()
        parsed = obs.parse_openmetrics(tf.read_text())
        assert parsed["counters"] or parsed["histograms"]
