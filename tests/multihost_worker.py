"""Worker for the 2-process multi-controller test (spawned by
``test_multihost_two_process``).

The multi-host analog of the reference's GASNet-substrate cluster runs
(env/chpl-env-*.sh + SPMD per-locale setup, Diagonalize.chpl:298-325):
``jax.distributed`` over two processes, each owning 4 CPU devices of a
global 8-device mesh.  Every engine mode builds its structures from
process-addressable shards only; matvec + Lanczos must agree with the
single-process truth.

Usage: multihost_worker.py <pid> <nproc> <port> [shards_path]
"""

import os
import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "true"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from distributed_matvec_tpu.parallel.mesh import init_distributed

init_distributed(coordinator_address=f"127.0.0.1:{port}",
                 num_processes=nproc, process_id=pid)

import numpy as np

assert len(jax.devices()) == 4 * nproc, jax.devices()
assert jax.process_count() == nproc

from distributed_matvec_tpu.models.basis import SpinBasis
from distributed_matvec_tpu.models.yaml_io import operator_from_dict
from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.solve import lanczos

N_SPINS = 12
E0_OVER_4 = -5.3873909174          # exact 12-site ring ground state / 4

basis = SpinBasis(number_spins=N_SPINS, hamming_weight=N_SPINS // 2)
basis.build()
op = operator_from_dict({"terms": [{
    "expression": "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁",
    "sites": [[i, (i + 1) % N_SPINS] for i in range(N_SPINS)]}]}, basis)

x = np.random.default_rng(7).standard_normal(basis.number_states)
want = op.matvec_host(x)


def _finish_obs():
    """Close this rank's telemetry stream: final registry totals (drains
    any pending health-probe fetches) + flush, so the run directory is
    complete for ``obs_report merge``/``report`` the moment we exit."""
    from distributed_matvec_tpu import obs

    obs.emit("metrics_snapshot", metrics=obs.snapshot())
    obs.flush()


if os.environ.get("DMT_MH_TRACE"):
    # Trimmed leg for the end-to-end TRACING test: a streamed engine per
    # rank over a RANK-LOCAL mesh (same CPU-backend constraint as the
    # fast leg below) driven by a small block-Lanczos solve — every eager
    # apply nests apply ⊂ iteration ⊂ solve in the span stack, the chunk
    # loop adds chunk spans, and both ranks agree on one trace id through
    # the shared run directory.  Correctness still asserted so a broken
    # solve cannot masquerade as a tracing pass.
    from distributed_matvec_tpu.parallel.mesh import make_mesh
    from distributed_matvec_tpu.solve import lanczos_block

    eng = DistributedEngine(op, mesh=make_mesh(devices=jax.local_devices()),
                            mode="streamed")
    res = lanczos_block(eng.matvec, k=1, tol=1e-8, max_iters=24, seed=3)
    e0 = float(res.eigenvalues[0])
    print(f"[p{pid}] trace leg: E0/4 = {e0 / 4:.10f} "
          f"({res.num_iters} iters)", flush=True)
    assert abs(e0 / 4 - E0_OVER_4) < 5e-3, e0   # truncated solve: coarse
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

if os.environ.get("DMT_MH_EXPORT"):
    # OpenMetrics-export leg (tests/test_slo.py): each rank of a REAL
    # 2-process job serves its own /metrics + /healthz on
    # DMT_OBS_PORT + rank (the side-by-side endpoint contract of
    # obs/export.py) while rank 0's /metrics aggregates rank 1's
    # textfile into one document.  Each rank scrapes BOTH endpoints and
    # asserts one consistent trace_id — the file-agreed id the shared
    # run directory distributes.  A small rank-local solve first (same
    # CPU-backend constraint as every fast leg here) so the scraped
    # registries carry real solver series; correctness still asserted
    # so a broken solve cannot masquerade as an export pass.
    import time as _time
    import urllib.request

    from distributed_matvec_tpu import obs
    from distributed_matvec_tpu.parallel.mesh import make_mesh
    from distributed_matvec_tpu.solve import lanczos_block

    def _scrape(url, timeout_s=60.0):
        deadline = _time.monotonic() + timeout_s
        while True:                       # peers bind at their own pace
            try:
                return urllib.request.urlopen(url, timeout=5).read().decode()
            except Exception:
                if _time.monotonic() >= deadline:
                    raise
                _time.sleep(0.2)

    eng = DistributedEngine(op, mesh=make_mesh(devices=jax.local_devices()),
                            mode="ell")
    res = lanczos_block(eng.matvec, k=1, tol=1e-8, max_iters=24, seed=3)
    e0 = float(res.eigenvalues[0])
    assert abs(e0 / 4 - E0_OVER_4) < 5e-3, e0   # truncated solve: coarse

    base = int(os.environ["DMT_OBS_PORT"])
    server = obs.start_exporter()         # resolves DMT_OBS_PORT + rank
    assert server is not None and server.port == base + pid, \
        (server and server.port, base, pid)
    obs.write_textfile()                  # what rank 0's scrape aggregates

    import json as _json
    tids = set()
    for r in range(nproc):
        health = _json.loads(_scrape(f"http://127.0.0.1:{base + r}/healthz"))
        assert health["status"] == "ok" and health["rank"] == r, health
        tids.add(health.get("trace_id"))
    assert tids == {obs.trace_id()}, (tids, obs.trace_id())

    if pid == 0:
        # rank 0's own endpoint merges the peer textfile: one scrape,
        # every rank's samples, disjoint by the rank label
        peer_tf = obs.textfile_path(rank=1)
        deadline = _time.monotonic() + 60.0
        while not os.path.exists(peer_tf):
            assert _time.monotonic() < deadline, f"no peer textfile {peer_tf}"
            _time.sleep(0.2)
        agg = _scrape(f"http://127.0.0.1:{base}/metrics")
        assert 'rank="0"' in agg and 'rank="1"' in agg, agg[:400]
    print(f"[p{pid}] EXPORT_TRACE_ID {obs.trace_id()}", flush=True)
    # file barrier before shutdown: a rank must keep serving until the
    # PEER has finished scraping it, or the cross-scrape above races the
    # teardown
    mine = os.path.join(obs.run_dir(), f"rank_{pid}", "export_done")
    with open(mine, "w") as f:
        f.write("done\n")
    peer = os.path.join(obs.run_dir(), f"rank_{1 - pid}", "export_done")
    deadline = _time.monotonic() + 60.0
    while not os.path.exists(peer):
        assert _time.monotonic() < deadline, f"peer never finished: {peer}"
        _time.sleep(0.1)
    obs.stop_exporter()
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

if os.environ.get("DMT_MH_PIPE") is not None:
    # Pipelined-apply leg for the barrier gate (tools/pipeline_check.py
    # and tests/test_engine_pipelined.py): a streamed engine per rank
    # over a RANK-LOCAL mesh (the CPU backend cannot run cross-process
    # computations — same constraint as the legs below), applied
    # repeatedly with a deterministic per-chunk staging latency injected
    # on rank 1 only (the parent arms DMT_FAULT=plan_upload:delay=...) —
    # the reproducible straggler.  Sequential applies pay that latency
    # INLINE, so rank 1's matvec_apply events lag further behind rank 0
    # every apply and `obs_report report --ranks` reads a growing
    # time-at-barrier; a pipeline_depth>=2 run stages the same chunks in
    # the prefetch workers, hides the same injected latency behind chunk
    # compute, and the barrier wait collapses — the >=2x cut the
    # acceptance gate asserts.  Correctness still asserted so a broken
    # pipeline cannot masquerade as a latency win.
    import time as _time

    from distributed_matvec_tpu.parallel.mesh import make_mesh

    depth = int(os.environ["DMT_MH_PIPE"])      # 0 = sequential leg
    eng = DistributedEngine(op, mesh=make_mesh(devices=jax.local_devices()),
                            mode="streamed", batch_size=32,
                            pipeline_depth=depth)
    xh = eng.to_hashed(x)
    yh = eng.matvec(xh)                 # warm-up: compile + first stream
    jax.block_until_ready(yh)
    napply = int(os.environ.get("DMT_MH_PIPE_APPLIES", "8"))
    t0 = _time.perf_counter()
    for _ in range(napply):
        yh = eng.matvec(xh)
    jax.block_until_ready(yh)
    steady_ms = (_time.perf_counter() - t0) / napply * 1e3
    err = float(np.abs(eng.from_hashed(yh) - want).max())
    print(f"[p{pid}] pipe depth={depth}: steady {steady_ms:.3f} ms/apply, "
          f"max err {err:.3e}", flush=True)
    assert err < 1e-12, err
    print(f"[p{pid}] PIPE_STEADY_MS {steady_ms:.4f}", flush=True)
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

if os.environ.get("DMT_MH_ELASTIC"):
    # Elastic leg (tests/test_elastic.py): topology-portable checkpoints
    # across a REAL 2-process job.  Each rank solves on a RANK-LOCAL
    # 4-device mesh (the CPU backend cannot run cross-process
    # computations — same constraint as every fast leg here) with
    # sharded per-rank checkpointing: the truncated first solve writes
    # `elastic_ck.h5.r<rank>` files at D=4, then the SAME solve resumes
    # on a 2-device rank-local mesh — the restore finds the old-topology
    # .r* files, reshards 4→2 (parallel/reshard.py), carries the
    # iteration count, and lands the exact ring ground state.
    from distributed_matvec_tpu.parallel.mesh import make_mesh

    scratch = os.environ["DMT_MH_ELASTIC"]
    ck = os.path.join(scratch, "elastic_ck.h5")
    eng4 = DistributedEngine(op, mesh=make_mesh(devices=jax.local_devices()),
                             mode="ell")
    part = lanczos(eng4.matvec, v0=eng4.random_hashed(seed=5), k=1,
                   tol=1e-12, max_iters=12, check_every=4,
                   checkpoint_path=ck, checkpoint_every=1)
    assert not part.converged
    eng2 = DistributedEngine(op,
                             mesh=make_mesh(devices=jax.local_devices()[:2]),
                             mode="ell")
    res = lanczos(eng2.matvec, v0=eng2.random_hashed(seed=5), k=1,
                  tol=1e-9, max_iters=400, check_every=8,
                  checkpoint_path=ck)
    assert res.resumed_from == 12, res.resumed_from
    e0 = float(res.eigenvalues[0])
    print(f"[p{pid}] elastic resumed E0/4 = {e0 / 4:.10f}", flush=True)
    assert abs(e0 / 4 - E0_OVER_4) < 1e-7, e0
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

if os.environ.get("DMT_MH_SERVE"):
    # Solve-service leg (tests/test_serve.py): two SAME-BASIS jobs
    # submitted to a scheduler whose engine pool runs over a RANK-LOCAL
    # mesh (the CPU backend cannot run cross-process computations — same
    # constraint as every fast leg here) inside a real 2-process
    # jax.distributed job.  The jobs must provably share ONE engine
    # build: the pool reports builds == 1 and the parent asserts exactly
    # one engine_init event per rank.  Correctness still asserted (both
    # jobs' E0 against the exact ring ground state) so a broken batch
    # cannot masquerade as a sharing win.
    from distributed_matvec_tpu.parallel.mesh import make_mesh
    from distributed_matvec_tpu.serve import (EnginePool, JobQueue,
                                              JobSpec, Scheduler)

    mesh = make_mesh(devices=jax.local_devices())
    pool = EnginePool(mesh=mesh)
    # block_width=1: the two jobs run as two consecutive solo batches, so
    # the second MUST come from the pool (builds=1, hits=1) — the
    # sharing-across-batches contract, stronger than one 2-wide batch
    sched = Scheduler(queue=JobQueue(), pool=pool, rates=None,
                      block_width=1)
    specs = [JobSpec(job_id=f"mh{i}",
                     basis={"number_spins": N_SPINS,
                            "hamming_weight": N_SPINS // 2},
                     k=1, tol=1e-9, max_iters=200, mode="ell",
                     n_devices=len(jax.local_devices()))
             for i in range(2)]
    for s in specs:
        sched.submit(s)
    n_done = sched.drain(scan_spool=False)
    assert n_done == 2, n_done
    for s in specs:
        rec = sched.queue.result(s.job_id)
        assert rec["status"] == "done", rec
        e0 = rec["eigenvalues"][0]
        assert abs(e0 / 4 - E0_OVER_4) < 1e-7, (s.job_id, e0)
    assert pool.builds == 1 and pool.hits == 1, (pool.builds, pool.hits)
    print(f"[p{pid}] SERVE_OK builds={pool.builds} hits={pool.hits}",
          flush=True)
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

if os.environ.get("DMT_MH_HYBRID"):
    # Hybrid-split leg (tests/test_engine_hybrid.py): a streamed and a
    # hybrid engine per rank over a RANK-LOCAL mesh (the CPU backend
    # cannot run cross-process computations — same constraint as every
    # fast leg here) inside a real 2-process jax.distributed job.  The
    # env value is the hybrid_split policy (a pinned mixed split by
    # default: the census/codec agreement paths still exercise, and the
    # split is deterministic per rank by construction).  The hybrid
    # apply must equal the streamed apply BIT-for-bit on both ranks, its
    # partial-term plan must be smaller than the streamed (same-tier)
    # plan, and correctness is still asserted against the host truth so
    # a broken merge cannot masquerade as a bytes win.
    from distributed_matvec_tpu.parallel.mesh import make_mesh
    from distributed_matvec_tpu.utils.config import update_config

    split = os.environ["DMT_MH_HYBRID"]
    update_config(stream_compress="lossless")
    eng_s = DistributedEngine(op,
                              mesh=make_mesh(devices=jax.local_devices()),
                              mode="streamed", batch_size=64)
    eng_h = DistributedEngine(op,
                              mesh=make_mesh(devices=jax.local_devices()),
                              mode="hybrid", batch_size=64,
                              hybrid_split=split)
    ys = np.asarray(eng_s.matvec(eng_s.to_hashed(x)))
    yh = np.asarray(eng_h.matvec(eng_h.to_hashed(x)))
    assert np.array_equal(ys, yh), "hybrid lost bit-identity to streamed"
    err = float(np.abs(eng_h.from_hashed(yh) - want).max())
    print(f"[p{pid}] hybrid split={split}: max err {err:.3e}, "
          f"plan {eng_h.plan_bytes} vs streamed {eng_s.plan_bytes} B",
          flush=True)
    assert err < 1e-12, err
    assert 0.0 < eng_h.hybrid_stream_fraction < 1.0, \
        eng_h.hybrid_stream_fraction
    assert eng_h.plan_bytes < eng_s.plan_bytes, \
        (eng_h.plan_bytes, eng_s.plan_bytes)
    print(f"[p{pid}] HYBRID_PLAN_BYTES {eng_h.plan_bytes} "
          f"{eng_s.plan_bytes}", flush=True)
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

if os.environ.get("DMT_MH_DYN"):
    # Dynamics leg (tests/test_dynamics.py, DESIGN.md §29): a streamed
    # engine per rank over a RANK-LOCAL mesh (the CPU backend cannot run
    # cross-process computations — same constraint as every fast leg
    # here) inside a real 2-process jax.distributed job, driving BOTH
    # dynamics solvers.  The rank-local problems are identical, so the
    # parent asserts the printed KPM moment and the evolve energy agree
    # across ranks to full precision — a broken recurrence cannot
    # masquerade as a telemetry pass — and exactly one engine_init per
    # rank (the plan is built once and reused across all moments AND
    # the whole trajectory).
    from distributed_matvec_tpu.parallel.mesh import make_mesh
    from distributed_matvec_tpu.solve import kpm_moments, krylov_evolve

    eng = DistributedEngine(op, mesh=make_mesh(devices=jax.local_devices()),
                            mode="streamed")
    kres = kpm_moments(eng.matvec, n_moments=48, n_vectors=2, seed=6)
    assert abs(kres.moments[0] - 1.0) < 1e-12, kres.moments[0]
    assert np.all(np.isfinite(kres.moments))
    assert np.all(np.abs(kres.moments) <= 1.0 + 1e-9), \
        np.abs(kres.moments).max()
    eres = krylov_evolve(eng.matvec, t_final=0.5, krylov_dim=12,
                         tol=1e-12, seed=6)
    assert eres.norm_drift < 1e-10, eres.norm_drift
    assert eres.energy_drift < 1e-10, eres.energy_drift
    print(f"[p{pid}] DYN_MU1 {kres.moments[1]:.15e}", flush=True)
    print(f"[p{pid}] DYN_E {eres.energies[0]:.15e}", flush=True)
    print(f"[p{pid}] dyn: {eres.num_steps} evolve steps, "
          f"{kres.num_applies} kpm applies", flush=True)
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

if os.environ.get("DMT_MH_TUNE"):
    # Autotune leg (tests/test_autotune.py, DESIGN.md §30): a tune=static
    # streamed engine per rank over a RANK-LOCAL mesh (the CPU backend
    # cannot run cross-process computations — same constraint as every
    # fast leg here) inside a real 2-process jax.distributed job.  Each
    # rank runs the same deterministic knob search, then the engine's
    # agree_config allgather adopts rank 0's row — the parent asserts
    # both ranks PRINT the same tuned token, so the fleet can never
    # split into two programs.  Bit-identity of the tuned apply against
    # an untuned streamed engine rides along (the tuner only picks
    # value-exact knobs), and correctness is still asserted against the
    # host truth so a broken tuned plan cannot masquerade as agreement.
    from distributed_matvec_tpu.parallel.mesh import make_mesh
    from distributed_matvec_tpu.utils.config import update_config

    update_config(tune="static")
    eng_t = DistributedEngine(op,
                              mesh=make_mesh(devices=jax.local_devices()),
                              mode="streamed")
    update_config(tune="off")
    assert eng_t._tuned is not None
    token = eng_t._tuned.token()
    eng_s = DistributedEngine(op,
                              mesh=make_mesh(devices=jax.local_devices()),
                              mode="streamed")
    yt = np.asarray(eng_t.matvec(eng_t.to_hashed(x)))
    ys = np.asarray(eng_s.matvec(eng_s.to_hashed(x)))
    assert np.array_equal(yt, ys), "tuned engine lost bit-identity"
    err = float(np.abs(eng_t.from_hashed(eng_t.matvec(
        eng_t.to_hashed(x))) - want).max())
    print(f"[p{pid}] tune leg: {token} max err {err:.3e}", flush=True)
    assert err < 1e-12, err
    print(f"[p{pid}] TUNE_CONFIG {token}", flush=True)
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

if os.environ.get("DMT_MH_FAST"):
    # Trimmed leg for the cross-rank OBSERVABILITY test: one ell engine
    # per rank over a RANK-LOCAL mesh (all engine collectives stay
    # intra-process, so the leg also runs on CPU backends whose client
    # cannot execute cross-process computations — the telemetry is still
    # rank-tagged by the real 2-process jax.distributed job), a handful of
    # eager applies (each emits a rank-tagged matvec_apply event — the raw
    # material of the straggler report), then the closing snapshot.
    # Correctness still asserted so a broken exchange cannot masquerade as
    # a telemetry pass.
    from distributed_matvec_tpu.parallel.mesh import make_mesh

    eng = DistributedEngine(op, mesh=make_mesh(devices=jax.local_devices()),
                            mode="ell")
    xh = eng.to_hashed(x)
    for _ in range(4):
        yh = eng.matvec(xh)
    y = eng.from_hashed(yh)
    err = float(np.abs(y - want).max())
    print(f"[p{pid}] fast ell: matvec max err {err:.3e}", flush=True)
    assert err < 1e-12, err
    # streamed leg, same rank-local-mesh pattern: the plan build's
    # shard_map collectives (the betas all_to_all) stay intra-process —
    # the CPU backend cannot run true multiprocess computations — while
    # the plan_stream/plan-upload telemetry is still tagged by the real
    # 2-process job.  Streamed must equal the ell engine's answer.
    eng_s = DistributedEngine(op,
                              mesh=make_mesh(devices=jax.local_devices()),
                              mode="streamed")
    ys = eng_s.from_hashed(eng_s.matvec(eng_s.to_hashed(x)))
    err_s = float(np.abs(ys - want).max())
    print(f"[p{pid}] fast streamed: matvec max err {err_s:.3e}", flush=True)
    assert err_s < 1e-12, err_s
    assert eng_s.plan_bytes > 0
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

if os.environ.get("DMT_MH_PROF"):
    # Continuous-profiling leg (tests/test_profile.py, DESIGN.md §32):
    # each rank of a REAL 2-process job AOT-analyzes the same rank-local
    # ell apply program, recording its HLO cost profile.  The profile is
    # content-addressed by the optimized HLO text, so agreement is
    # structural: both ranks must print the same fingerprint and totals
    # (the parent asserts it) and their artifacts land on the SAME
    # content-addressed path in the shared artifact root — a fleet whose
    # ranks compile different apply programs cannot agree.  Correctness
    # still asserted so a broken apply cannot masquerade as a profiling
    # pass.
    from distributed_matvec_tpu import obs
    from distributed_matvec_tpu.parallel.mesh import make_mesh

    eng = DistributedEngine(op, mesh=make_mesh(devices=jax.local_devices()),
                            mode="ell")
    xh = eng.to_hashed(x)
    err = float(np.abs(eng.from_hashed(eng.matvec(xh)) - want).max())
    print(f"[p{pid}] prof ell: matvec max err {err:.3e}", flush=True)
    assert err < 1e-12, err
    eng.apply_memory_analysis(xh)
    profs = [p for p in obs.executable_costs().values()
             if p["program"] == "distributed_ell_apply"]
    assert len(profs) == 1, sorted(
        p["program"] for p in obs.executable_costs().values())
    prof = profs[0]
    t = prof["totals"]
    for axis in ("bytes", "flops"):
        s = sum(row[axis] for row in prof["phases"].values())
        assert abs(s - t[axis]) < 0.5, (axis, s, t[axis])
    art = prof.get("artifact", "")
    assert art and os.path.exists(art), \
        f"no content-addressed profile artifact ({art!r})"
    print(f"[p{pid}] PROF_OK {prof['fingerprint']} {t['flops']:.0f} "
          f"{t['bytes']:.0f} {os.path.basename(art)}", flush=True)
    _finish_obs()
    print(f"[p{pid}] MULTIHOST_OK", flush=True)
    sys.exit(0)

for mode in ("ell", "compact", "fused"):
    eng = DistributedEngine(op, n_devices=4 * nproc, mode=mode)
    y = eng.from_hashed(eng.matvec(eng.to_hashed(x)))
    err = float(np.abs(y - want).max())
    print(f"[p{pid}] {mode}: matvec max err {err:.3e}", flush=True)
    assert err < 1e-12, (mode, err)

res = lanczos(eng.matvec, v0=eng.random_hashed(seed=3), k=1, tol=1e-9)
e0 = float(res.eigenvalues[0])
print(f"[p{pid}] lanczos E0/4 = {e0 / 4:.10f}", flush=True)
assert abs(e0 / 4 - E0_OVER_4) < 1e-7

# multi-process LOBPCG: the unjitted lobpcg body runs under our jit with
# the engine operands as arguments; start block generated per shard
from distributed_matvec_tpu.solve import lobpcg

evals_b, V_b, iters_b = lobpcg(eng.matvec, basis.number_states, k=2,
                               tol=1e-8)
print(f"[p{pid}] lobpcg E0/4 = {evals_b[0] / 4:.10f} ({iters_b} iters)",
      flush=True)
assert abs(evals_b[0] / 4 - E0_OVER_4) < 1e-6
assert V_b.shape == (basis.number_states, 2)

# shard-native construction in a multi-controller run: every process
# loads only its addressable shards from the (pre-written) shard file,
# the basis is never built globally, and the solve stays hashed.  The
# engine uses a PLAN mode (compact) with a per-shard structure cache —
# the multi-process shard-local build + checkpoint of VERDICT r3 #3 —
# and the Lanczos solve checkpoints per shard: a budget-truncated first
# solve resumes in a second call (VERDICT r3 #8's killed-solve resume,
# both inside this 2-process run).
shards_path = sys.argv[4] if len(sys.argv) > 4 else None
if shards_path:
    import os as _os

    scratch = _os.path.dirname(shards_path)
    cache = _os.path.join(scratch, "plan_cache.h5")
    solver_ck = _os.path.join(scratch, "solver_ck.h5")

    def make_engine():
        fresh = SpinBasis(number_spins=N_SPINS, hamming_weight=N_SPINS // 2)
        op2 = operator_from_dict({"terms": [{
            "expression": "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁",
            "sites": [[i, (i + 1) % N_SPINS] for i in range(N_SPINS)]}]},
            fresh)
        eng = DistributedEngine.from_shards(
            op2, shards_path, n_devices=4 * nproc, mode="compact",
            structure_cache=cache)
        assert not fresh.is_built
        return eng

    eng2 = make_engine()
    assert not eng2.structure_restored
    y2 = eng2.from_hashed(eng2.matvec(eng2.to_hashed(x)))
    err2 = float(np.abs(y2 - want).max())
    print(f"[p{pid}] from_shards compact: matvec max err {err2:.3e}",
          flush=True)
    assert err2 < 1e-12, err2

    # per-shard plan cache restore (each rank wrote/reads its own .r file)
    eng3 = make_engine()
    assert eng3.structure_restored
    y3 = eng3.from_hashed(eng3.matvec(eng3.to_hashed(x)))
    assert float(np.abs(y3 - y2).max()) == 0.0

    # PARTIAL cache: drop one rank's sidecar — restore must be refused on
    # EVERY rank (all-or-nothing agreement), not hang half the job in the
    # rebuild's collectives
    from jax.experimental import multihost_utils

    if pid == 1:
        _os.remove(f"{cache}.dist{4 * nproc}.structure.h5.r1")
    multihost_utils.sync_global_devices("partial_cache_ready")
    eng4 = make_engine()
    assert not eng4.structure_restored
    y4 = eng4.from_hashed(eng4.matvec(eng4.to_hashed(x)))
    assert float(np.abs(y4 - y2).max()) == 0.0
    print(f"[p{pid}] partial-cache rebuild agreed", flush=True)

    # budget-truncated solve checkpoints per shard, rerun resumes
    v0 = eng3.random_hashed(seed=4)
    part = lanczos(eng3.matvec, v0=v0, k=1, tol=1e-12, max_iters=12,
                   check_every=4, checkpoint_path=solver_ck,
                   checkpoint_every=1)
    assert not part.converged
    res2 = lanczos(eng3.matvec, v0=v0, k=1, tol=1e-9, max_iters=400,
                   check_every=8, checkpoint_path=solver_ck)
    assert res2.resumed_from == 12, res2.resumed_from
    e0s = float(res2.eigenvalues[0])
    print(f"[p{pid}] from_shards resumed E0/4 = {e0s / 4:.10f}", flush=True)
    assert abs(e0s / 4 - E0_OVER_4) < 1e-7

_finish_obs()
print(f"[p{pid}] MULTIHOST_OK", flush=True)
