"""End-to-end solve tracing (obs/trace.py) + the trace/watch readers.

Covers: trace-id resolution (env pin, run-dir file agreement, random
fallback), span stack nesting + envelope stamping, the provable-no-op
contracts (DMT_OBS=off, DMT_TRACE=off), engine apply spans, the
stall-report span attachment, the Perfetto export's B/E pairing +
nesting, a golden `watch --once` frame, bench-trend run identity, and
the REAL 2-process spawned leg asserting cross-rank trace agreement and
a Perfetto round-trip.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.obs import trace as obs_trace

from test_operator import build_heisenberg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def clean_trace():
    obs.reset_all()
    yield
    obs.reset_all()


# ---------------------------------------------------------------------------
# identity


def test_trace_id_lazy_and_stable(clean_trace):
    a = obs.trace_id()
    assert a and len(a) == 16
    assert obs.trace_id() == a              # cached for the process
    assert obs.job_id() == a                # defaults to the trace id
    obs.reset_all()
    assert obs.trace_id() != a              # reset re-keys


def test_trace_id_env_pin(clean_trace, monkeypatch):
    monkeypatch.setenv("DMT_TRACE_ID", "cafef00d")
    assert obs.trace_id() == "cafef00d"


def test_job_id_env_and_config(clean_trace, monkeypatch):
    monkeypatch.setenv("DMT_JOB_ID", "job-42")
    assert obs.job_id() == "job-42"
    ev = obs.emit("x")
    assert ev["job_id"] == "job-42"
    assert ev["trace_id"] != "job-42"       # trace identity stays its own


def test_trace_id_file_agreement(tmp_path):
    """First rank's O_EXCL create wins; later ranks read the winner."""
    d = str(tmp_path / "run")
    a = obs_trace._agree_trace_id(d, "aaaa")
    b = obs_trace._agree_trace_id(d, "bbbb")
    assert a == "aaaa" and b == "aaaa"
    with open(os.path.join(d, "trace_id")) as f:
        assert f.read().strip() == "aaaa"


def test_trace_id_agreement_via_run_dir(clean_trace, tmp_path, monkeypatch):
    monkeypatch.setenv("DMT_OBS_DIR", str(tmp_path / "run"))
    tid = obs.trace_id()
    with open(tmp_path / "run" / "trace_id") as f:
        assert f.read().strip() == tid


# ---------------------------------------------------------------------------
# spans + stamping


def test_span_nesting_and_envelope(clean_trace):
    with obs.span("solve", kind="solve", solver="t") as sp_solve:
        with obs.span("iteration", kind="iteration", iter=0):
            assert obs.span_path() == "solve>iteration"
            with obs.span("apply", kind="apply", apply=0) as sp_apply:
                deep = obs.deepest_span()
                assert deep["name"] == "apply" and deep["apply"] == 0
                ev = obs.emit("matvec_apply", wall_ms=1.0)
                assert ev["span_id"] == sp_apply.sid
                assert ev["trace_id"] == obs.trace_id()
    assert obs.open_spans() == []
    spans = obs.events("span")
    assert [e["name"] for e in spans] == ["apply", "iteration", "solve"]
    by_id = {e["span_id"]: e for e in spans}
    # span events stamp their OWN id (emitted before the pop) and carry
    # the parent link; the chain roots at the solve span
    apply_ev = next(e for e in spans if e["name"] == "apply")
    it_ev = by_id[apply_ev["parent_span_id"]]
    assert it_ev["name"] == "iteration"
    assert by_id[it_ev["parent_span_id"]]["name"] == "solve"
    assert by_id[it_ev["parent_span_id"]]["parent_span_id"] is None
    assert spans[-1]["span_id"] == sp_solve.sid
    for e in spans:
        assert e["dur_ms"] >= 0 and e["t0"] <= e["ts"]


def test_span_payload_cannot_spoof_envelope(clean_trace):
    with obs.span("s", kind="solve") as sp:
        ev = obs.emit("x", span_id="forged", trace_id="forged")
    assert ev["span_id"] == sp.sid
    assert ev["trace_id"] == obs.trace_id()


def test_obs_off_is_noop(clean_trace, monkeypatch):
    monkeypatch.setenv("DMT_OBS", "off")
    from contextlib import nullcontext
    assert isinstance(obs.span("x"), nullcontext)
    assert obs.trace_id() is None and obs.job_id() is None
    with obs.span("x"):
        assert obs.emit("y") is None
    monkeypatch.delenv("DMT_OBS")
    assert obs.events("span") == []         # nothing leaked through


def test_trace_off_keeps_events_unstamped(clean_trace, monkeypatch):
    monkeypatch.setenv("DMT_TRACE", "off")
    with obs.span("x", kind="solve"):
        ev = obs.emit("y")
    assert ev is not None
    assert "trace_id" not in ev and "span_id" not in ev
    assert obs.events("span") == []


def test_exception_closes_span(clean_trace):
    with pytest.raises(RuntimeError):
        with obs.span("solve", kind="solve"):
            raise RuntimeError("boom")
    assert obs.open_spans() == []
    assert [e["name"] for e in obs.events("span")] == ["solve"]


# ---------------------------------------------------------------------------
# engine + solver integration


def test_local_engine_apply_span(clean_trace, rng):
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10)
    eng = LocalEngine(op, mode="ell")
    x = rng.standard_normal(op.basis.number_states)
    eng.matvec(x)
    eng.matvec(x)
    spans = [e for e in obs.events("span") if e["cat"] == "apply"]
    assert [e["apply"] for e in spans] == [0, 1]
    assert all(e["engine"] == "local" for e in spans)
    applies = obs.events("matvec_apply")
    # the matvec_apply event is emitted INSIDE its apply span
    assert [e["span_id"] for e in applies] == [e["span_id"] for e in spans]
    phases = obs.events("apply_phases")
    assert [e["span_id"] for e in phases] == [e["span_id"] for e in spans]


def test_solver_spans_root_and_nest(clean_trace, rng):
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    from distributed_matvec_tpu.solve import lanczos

    op = build_heisenberg(10)
    eng = LocalEngine(op, mode="ell")
    lanczos(eng.matvec, op.basis.number_states, k=1, tol=1e-8,
            max_iters=48)
    spans = obs.events("span")
    solves = [e for e in spans if e["cat"] == "solve"]
    iters = [e for e in spans if e["cat"] == "iteration"]
    assert len(solves) == 1 and solves[0]["name"] == "lanczos"
    assert iters and all(
        e["parent_span_id"] == solves[0]["span_id"] for e in iters)
    # acyclic + rooted at the solve span
    by_id = {e["span_id"]: e for e in spans}
    for e in spans:
        seen = set()
        cur = e
        while cur.get("parent_span_id"):
            assert cur["span_id"] not in seen
            seen.add(cur["span_id"])
            cur = by_id[cur["parent_span_id"]]
        assert cur["span_id"] == solves[0]["span_id"]
    # the lanczos_trace convergence events attribute to iteration or solve
    for ev in obs.events("lanczos_trace"):
        assert ev.get("trace_id") == obs.trace_id()


def test_stall_report_carries_deepest_span(clean_trace, tmp_path):
    from distributed_matvec_tpu.parallel.heartbeat import HeartbeatWatchdog

    d = str(tmp_path / "run")
    hb = os.path.join(d, "heartbeat")
    os.makedirs(hb)
    stale = os.path.join(hb, "rank_1.hb")
    with open(stale, "w") as f:
        f.write("0\n")
    os.utime(stale, (1.0, 1.0))
    reports = []
    with obs.span("solve", kind="solve"), \
            obs.span("apply", kind="apply", apply=7), \
            obs.span("chunk", kind="chunk", chunk=3):
        wd = HeartbeatWatchdog(d, interval_s=0.05, timeout_s=0.4, rank=0,
                               n_ranks=2, on_stall=reports.append)
        wd.start()
        wd._thread.join(timeout=10)
        wd.stop()
    assert len(reports) == 1
    rep = reports[0]
    # the watchdog names what THIS rank was doing: the deepest open span
    # (phase/chunk granule) plus the full ancestry
    assert rep["span"]["kind"] == "chunk" and rep["span"]["chunk"] == 3
    assert rep["span_path"] == "solve>apply>chunk"
    ev = obs.events("stall_report")[0]
    assert ev["span"]["chunk"] == 3
    assert ev["span_id"] == rep["span"]["span_id"]


# ---------------------------------------------------------------------------
# Perfetto export + watch (reader side, synthetic streams)


def _synthetic_run(tmp_path):
    """A deterministic 2-rank recorded run exercising every watch/trace
    section: spans (solve > iteration > apply > chunk), apply_phases,
    lanczos_trace, watermarks, drift, a straggling rank 1."""
    t0 = 1_700_000_000.0
    evs = {0: [], 1: []}
    for r in (0, 1):
        seq = 0

        def E(kind, ts, **f):
            nonlocal seq
            ev = {"seq": seq, "ts": round(ts, 6), "proc": r, "rank": r,
                  "n_ranks": 2, "kind": kind, "trace_id": "feedc0de",
                  "job_id": "job-7", **f}
            seq += 1
            evs[r].append(ev)
            return ev

        solve_id = "1-solve"
        for i in range(3):
            # rank 1's lag GROWS per apply: genuine compute straggle that
            # survives the constant-offset skew correction
            lag = 0.0 if r == 0 else 0.010 * i
            it_id = f"{2 + 2 * i}-iter"
            ap_id = f"{3 + 2 * i}-appl"
            ts_a = t0 + 1.0 * i + lag
            E("matvec_apply", ts_a + 0.050, engine="distributed",
              apply=i, wall_ms=50.0, bytes=1 << 20, span_id=ap_id)
            E("apply_phases", ts_a + 0.050, engine="distributed",
              mode="streamed", apply=i, wall_ms=50.0, span_id=ap_id,
              chunks=2, columns=1,
              phases={"plan_h2d": {"bytes": 1 << 20, "gathers": 0,
                                   "flops": 0, "wall_ms": 10.0},
                      "compute": {"bytes": 3 << 20, "gathers": 100,
                                  "flops": 100},
                      "exchange": {"bytes": 1 << 20, "gathers": 0,
                                   "flops": 0},
                      "accumulate": {"bytes": 1 << 18, "gathers": 10,
                                     "flops": 10}},
              bytes_total=0, gathers_total=0, flops_total=0)
            E("span", ts_a + 0.020, name="chunk", cat="chunk", chunk=0,
              span_id=f"c{i}0", parent_span_id=ap_id, t0=ts_a,
              dur_ms=20.0)
            E("span", ts_a + 0.045, name="chunk", cat="chunk", chunk=1,
              span_id=f"c{i}1", parent_span_id=ap_id, t0=ts_a + 0.022,
              dur_ms=23.0)
            E("span", ts_a + 0.050, name="apply", cat="apply",
              engine="distributed", mode="streamed", apply=i,
              span_id=ap_id, parent_span_id=it_id, t0=ts_a, dur_ms=50.0)
            E("lanczos_trace", ts_a + 0.060, solver="lanczos_block",
              iter=2 * (i + 1), basis_size=2 * (i + 1),
              ritz=[-21.0 - i], residual=[10.0 ** -(i + 2)],
              span_id=it_id)
            E("span", ts_a + 0.070, name="iteration", cat="iteration",
              solver="lanczos_block", iter=2 * i, span_id=it_id,
              parent_span_id=solve_id, t0=ts_a - 0.005, dur_ms=75.0)
        lag = 0.0 if r == 0 else 0.020
        E("memory_watermark", t0 + 3.0 + lag, bytes_in_use=1 << 30,
          peak_bytes=(3 << 29) + (r << 20))
        E("compress_drift", t0 + 3.0 + lag, rel_err=2.5e-7, tier="bf16",
          engine="distributed", apply=2, chunk=0)
        E("solver_end", t0 + 3.2 + lag, solver="lanczos_block", iters=6,
          converged=True, eigenvalues=[-23.0], span_id=solve_id)
        E("span", t0 + 3.2 + lag, name="lanczos_block", cat="solve", k=1,
          span_id=solve_id, parent_span_id=None, t0=t0 + lag - 0.5,
          dur_ms=3700.0)
        d = tmp_path / f"rank_{r}"
        d.mkdir(parents=True, exist_ok=True)
        with open(d / "events.jsonl", "w") as f:
            for ev in evs[r]:
                f.write(json.dumps(ev) + "\n")
    return str(tmp_path)


def test_perfetto_export_nests_and_balances(tmp_path):
    rep = _load_tool("obs_report")
    run = _synthetic_run(tmp_path / "run")
    events = rep.load_events(run)
    trace = rep.perfetto_trace(events)
    # round-trips through json, loadable by Perfetto
    trace = json.loads(json.dumps(trace))
    te = trace["traceEvents"]
    rep.validate_trace_events(te)
    assert trace["otherData"]["trace_id"] == "feedc0de"
    assert trace["otherData"]["ranks"] == [0, 1]
    for pid in (0, 1):
        # track 0: B/E stack order solve > iteration > apply > chunk
        stack, seen = [], []
        for ev in te:
            if ev.get("pid") != pid or ev.get("tid") != 0:
                continue
            if ev.get("ph") == "B":
                stack.append(ev["cat"])
                seen.append(list(stack))
            elif ev.get("ph") == "E":
                stack.pop()
        assert ["solve"] in seen
        assert ["solve", "iteration", "apply", "chunk"] in seen
        # track 1: phases nested inside the per-apply wrapper slice
        stack, phase_depths = [], set()
        for ev in te:
            if ev.get("pid") != pid or ev.get("tid") != 1:
                continue
            if ev.get("ph") == "B":
                stack.append(ev["cat"])
                if ev["cat"] == "phase":
                    phase_depths.add(tuple(stack[:-1]))
            elif ev.get("ph") == "E":
                stack.pop()
        assert phase_depths == {("apply",)}
        # counter tracks landed
        names = {ev["name"] for ev in te
                 if ev.get("ph") == "C" and ev.get("pid") == pid}
        assert {"hbm_bytes_in_use", "ritz0", "residual_max",
                "compress_rel_err"} <= names


def test_watch_golden_frame(tmp_path):
    rep = _load_tool("obs_report")
    run = _synthetic_run(tmp_path / "run")
    frame = rep.watch_frame(rep.load_events(run))
    expected = """\
obs watch | trace feedc0de | job job-7 | 2 rank(s) | 50 events
--------------------------------------------------------------
applies   rank0: 3 (0.05/s, last 50.0 ms)   rank1: 3 (0.05/s, last 50.0 ms)
phases    distributed/streamed: plan_h2d 20% | compute 56% | exchange 19% | accumulate 5%  (50.0 ms/apply)
solver    lanczos_block: iter 6, basis 6, ritz0 -23.00000000, max res 1.00e-04  [converged]
skew      rank1 waits 6.67 ms/apply at the barrier over 3 aligned applies (worst apply #0 rank 0 +7.5 ms)
health    warn 0, critical 0 | faults 0, io_retries 0, stalls 0 | drift 2.50e-07
memory    rank0: hbm 1.0 GB (peak 1.5 GB, host ledger -) | rank1: hbm 1.0 GB (peak 1.5 GB, host ledger -)"""
    assert frame == expected


def test_watch_once_cli(tmp_path, capsys):
    rep = _load_tool("obs_report")
    run = _synthetic_run(tmp_path / "run")
    assert rep.main(["watch", run, "--once"]) == 0
    out = capsys.readouterr().out
    assert "obs watch | trace feedc0de" in out
    assert "solver    lanczos_block" in out


def test_trace_cli_writes_export(tmp_path, capsys):
    rep = _load_tool("obs_report")
    run = _synthetic_run(tmp_path / "run")
    out_json = str(tmp_path / "trace.json")
    assert rep.main(["trace", run, "-o", out_json]) == 0
    with open(out_json) as f:
        trace = json.load(f)
    rep.validate_trace_events(trace["traceEvents"])


def test_trace_cli_pre_trace_stream(tmp_path, capsys):
    """Backward compat: a pre-trace event stream (no span events, no
    trace_id) exports an empty-but-valid trace and exits 2."""
    rep = _load_tool("obs_report")
    d = tmp_path / "run" / "rank_0"
    d.mkdir(parents=True)
    with open(d / "events.jsonl", "w") as f:
        f.write(json.dumps({"seq": 0, "ts": 1.0, "proc": 0, "rank": 0,
                            "kind": "engine_init"}) + "\n")
    assert rep.main(["trace", str(tmp_path / "run")]) == 2


def test_deepest_span_lock_timeout(clean_trace):
    """The watchdog-facing readers must not block forever on a held
    trace lock (a wedged main thread must still be abortable)."""
    with obs.span("solve", kind="solve"):
        assert obs.deepest_span(timeout=1.0)["name"] == "solve"
        obs_trace._lock.acquire()
        try:
            assert obs.deepest_span(timeout=0.05) is None
            assert obs.span_path(timeout=0.05) == ""
        finally:
            obs_trace._lock.release()


def test_watch_fold_carries_totals(tmp_path):
    """A live watch that trims its window still reports exact lifetime
    totals via the carried base aggregates."""
    rep = _load_tool("obs_report")
    old = [{"seq": i, "ts": 1.0 + i, "rank": 0, "n_ranks": 1,
            "kind": "matvec_apply", "apply": i, "wall_ms": 1.0,
            "bytes": 100} for i in range(5)]
    old.append({"seq": 5, "ts": 6.0, "rank": 0, "n_ranks": 1,
                "kind": "health", "check": "x", "level": "warn"})
    new = [{"seq": 6, "ts": 7.0, "rank": 0, "n_ranks": 1,
            "kind": "matvec_apply", "apply": 5, "wall_ms": 2.0,
            "bytes": 100}]
    base = rep.watch_fold(rep.empty_watch_base(), old)
    st = rep.watch_state(new, base=base)
    assert st["per_rank"][0]["applies"] == 6        # 5 folded + 1 live
    assert st["per_rank"][0]["bytes"] == 600
    assert st["health"]["warn"] == 1                # folded
    assert st["n_events"] == 7
    # without the base only the retained tail counts
    assert rep.watch_state(new)["per_rank"][0]["applies"] == 1


def test_watch_seed_consumes_exact_offsets(tmp_path):
    """The live-mode seed records the byte offset it actually read, so an
    append landing between seed and first poll is neither dropped nor
    double-counted — and a torn final line completes on the next poll."""
    rep = _load_tool("obs_report")
    f = str(tmp_path / "events.jsonl")
    full = json.dumps({"seq": 0, "ts": 1.0, "rank": 0, "kind": "a"})
    torn = json.dumps({"seq": 1, "ts": 2.0, "rank": 0, "kind": "b"})
    with open(f, "w") as fh:
        fh.write(full + "\n" + torn[:10])           # torn mid-write
    events, state, partial = rep._watch_seed([f])
    assert [e["kind"] for e in events] == ["a"]
    assert partial[f] == torn[:10]
    with open(f, "a") as fh:                        # writer finishes + one more
        fh.write(torn[10:] + "\n"
                 + json.dumps({"seq": 2, "ts": 3.0, "rank": 0,
                               "kind": "c"}) + "\n")
    got = rep._follow_poll([f], state, partial)
    assert [e["kind"] for e in got] == ["b", "c"]


# ---------------------------------------------------------------------------
# bench-trend run identity


def test_bench_trend_record_identity(tmp_path):
    bt = _load_tool("bench_trend")
    rec = bt.compact_record(
        {"cfg": {"config": "c", "device_ms": 1.0, "n_states": 10}},
        mode="smoke", backend="cpu", ts=1.0,
        trace_id="feedc0de", job_id="job-7", obs_dir="/tmp/run")
    assert rec["trace_id"] == "feedc0de"
    assert rec["job_id"] == "job-7"
    assert rec["obs_dir"] == "/tmp/run"
    p = str(tmp_path / "PROGRESS.jsonl")
    assert bt.append_record(p, rec)
    got = bt.load_records(p)[0]
    assert got["trace_id"] == "feedc0de"


def test_bench_trend_gates_drift_metrics():
    """compress_rel_err / compress_drift_max are default-gated and
    cost-like: error growth fires the gate."""
    bt = _load_tool("bench_trend")
    recs = [
        {"kind": "bench_trend", "ts": 1.0, "mode": "full", "backend":
         "cpu", "configs": {"s": {"n_states": 10, "compress_rel_err":
                                  1e-7, "compress_drift_max": 1e-7}}},
        {"kind": "bench_trend", "ts": 2.0, "mode": "full", "backend":
         "cpu", "configs": {"s": {"n_states": 10, "compress_rel_err":
                                  1e-5, "compress_drift_max": 1e-5}}},
    ]
    rows, regressions, newest = bt.gate(recs, threshold=0.3)
    assert {m for _, m, *_ in regressions} == {"compress_rel_err",
                                               "compress_drift_max"}


# ---------------------------------------------------------------------------
# the REAL 2-process spawned leg


def test_multihost_trace_two_ranks(tmp_path):
    """2-process run (multihost worker harness, trace leg): trace ids
    agree across ranks, parent links are acyclic and rooted at the solve
    span on each rank, and the Perfetto export round-trips with balanced,
    correctly nested B/E pairs on both rank tracks."""
    import socket
    import subprocess

    rep = _load_tool("obs_report")
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    run = tmp_path / "trace_run"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["DMT_MH_TRACE"] = "1"
    env["DMT_OBS_DIR"] = str(run)
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]

    events = rep.load_events(str(run))
    ranks = sorted({e["rank"] for e in events})
    assert ranks == [0, 1]
    # ONE trace id across both ranks (file-agreed through the run dir),
    # stamped on every event
    tids = {e.get("trace_id") for e in events}
    assert len(tids) == 1 and None not in tids
    assert all(e.get("job_id") == next(iter(tids)) for e in events)

    for r in ranks:
        spans = [e for e in events
                 if e["rank"] == r and e["kind"] == "span"]
        by_id = {e["span_id"]: e for e in spans}
        solves = [e for e in spans if e["cat"] == "solve"]
        assert len(solves) == 1
        kinds = {e["cat"] for e in spans}
        assert {"solve", "iteration", "apply", "chunk"} <= kinds
        # acyclic, rooted at the solve span
        for e in spans:
            seen = set()
            cur = e
            while cur.get("parent_span_id"):
                assert cur["span_id"] not in seen
                seen.add(cur["span_id"])
                cur = by_id[cur["parent_span_id"]]
            assert cur["span_id"] == solves[0]["span_id"]
        # every event of a traced run carries trace_id; in-span events
        # carry span_id pointing at a recorded span
        for e in events:
            if e["rank"] == r and e["kind"] in ("matvec_apply",
                                                "apply_phases"):
                assert e["span_id"] in by_id

    trace = json.loads(json.dumps(rep.perfetto_trace(events)))
    te = trace["traceEvents"]
    rep.validate_trace_events(te)
    for pid in ranks:
        seen = []
        stack = []
        for ev in te:
            if ev.get("pid") != pid or ev.get("tid") != 0:
                continue
            if ev.get("ph") == "B":
                stack.append(ev["cat"])
                seen.append(tuple(stack))
            elif ev.get("ph") == "E":
                stack.pop()
        assert ("solve", "iteration", "apply", "chunk") in seen, \
            f"rank {pid} track never nested solve>iteration>apply>chunk"
