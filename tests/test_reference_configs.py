"""Run the reference's own YAML config matrix end-to-end.

The reference's test matrix drives 13 matvec + 14 enumeration configs from
``data/*.yaml`` (``Makefile:88-126``).  The golden HDF5 archives are not
available offline, so ground truth is layered:

  * every config ≤ 24 sites: YAML → basis build → jitted engine matvec vs the
    independent host (NumPy) matvec at the golden tolerances,
  * configs ≤ 12 sites additionally: dense Kronecker/projector matrix
    (tests/dense_ref.py — fully independent of the production term compiler).

``issue_01.yaml`` is the reference's regression input (Makefile:111-125).
"""

import os

import numpy as np
import pytest
import yaml as pyyaml

import dense_ref
from distributed_matvec_tpu.models.expression import parse_expression
from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml
from distributed_matvec_tpu.parallel.engine import LocalEngine

DATA = "/root/reference/data"
ATOL, RTOL = 1e-13, 1e-12

SMALL = [  # dense-verified
    "heisenberg_chain_4.yaml",
    "heisenberg_chain_6.yaml",
    "heisenberg_chain_8.yaml",
    "heisenberg_chain_10.yaml",
    "heisenberg_chain_12.yaml",
    "heisenberg_kagome_12.yaml",
    "heisenberg_kagome_12_symm.yaml",
    "issue_01.yaml",
]
MEDIUM = [  # engine vs host matvec
    "heisenberg_chain_16.yaml",
    "heisenberg_chain_20.yaml",
    "heisenberg_square_4x4.yaml",
    "heisenberg_kagome_16.yaml",
]
LARGE = [  # symmetry-projected or multi-million-state, slow-marked
    "heisenberg_chain_24.yaml",
    "heisenberg_chain_24_symm.yaml",
]

require_data = pytest.mark.skipif(
    not os.path.isdir(DATA), reason="reference data not mounted"
)


def _load(name):
    cfg = load_config_from_yaml(os.path.join(DATA, name))
    assert cfg.hamiltonian is not None
    cfg.basis.build()
    return cfg


def _random_x(cfg, rng):
    x = rng.random(cfg.basis.number_states) - 0.5
    if not cfg.hamiltonian.effective_is_real:
        x = x.astype(np.complex128)
    return x


@require_data
@pytest.mark.parametrize("name", SMALL)
def test_small_configs_vs_dense(name, rng):
    cfg = _load(name)
    raw = pyyaml.safe_load(open(os.path.join(DATA, name)))
    pairs = [(parse_expression(t["expression"]), t["sites"])
             for t in raw["hamiltonian"]["terms"]]
    basis = cfg.basis
    h_full = dense_ref.operator_matrix_full(basis.number_spins, pairs)
    h_eff = dense_ref.projected_matrix(
        basis.number_spins, h_full, basis.representatives, basis.norms,
        basis.group)
    x = _random_x(cfg, rng)
    y_ref = h_eff @ x
    if cfg.hamiltonian.effective_is_real:
        y_ref = y_ref.real
    np.testing.assert_allclose(
        cfg.hamiltonian.matvec_host(x), y_ref, atol=ATOL, rtol=RTOL)
    eng = LocalEngine(cfg.hamiltonian, batch_size=97)
    np.testing.assert_allclose(
        np.asarray(eng.matvec(x)), y_ref, atol=ATOL, rtol=RTOL)


@require_data
@pytest.mark.parametrize("name", MEDIUM)
def test_medium_configs_engine_vs_host(name, rng):
    cfg = _load(name)
    x = _random_x(cfg, rng)
    eng = LocalEngine(cfg.hamiltonian)
    np.testing.assert_allclose(
        np.asarray(eng.matvec(x)), cfg.hamiltonian.matvec_host(x),
        atol=ATOL, rtol=RTOL)


@require_data
@pytest.mark.slow
@pytest.mark.parametrize("name", LARGE)
def test_large_symm_configs(name, rng):
    cfg = _load(name)
    x = _random_x(cfg, rng)
    eng = LocalEngine(cfg.hamiltonian)
    np.testing.assert_allclose(
        np.asarray(eng.matvec(x)), cfg.hamiltonian.matvec_host(x),
        atol=ATOL, rtol=RTOL)


@require_data
def test_enumeration_counts_match_sector_dimensions():
    """Enumeration sanity across the matrix: sector sizes obey the
    character-sum dimension formula (dense_ref projector ranks for the
    smallest, plain binomials for the unprojected)."""
    from math import comb

    for name, n, hw in [("heisenberg_chain_10.yaml", 10, 5),
                        ("heisenberg_chain_16.yaml", 16, 8),
                        ("heisenberg_kagome_16.yaml", 16, 8)]:
        cfg = load_config_from_yaml(os.path.join(DATA, name))
        cfg.basis.build()
        if not cfg.basis.requires_projection:
            assert cfg.basis.number_states == comb(n, hw)


@require_data
def test_full_yaml_matrix_loads():
    """Every in-tree YAML ≤ 40 sites parses through the schema loader
    (loadConfigFromYaml parity, ForeignTypes.chpl:261-288) — no build."""
    import glob

    for path in sorted(glob.glob(os.path.join(DATA, "*.yaml"))):
        cfg = load_config_from_yaml(path)
        assert cfg.basis.number_spins >= 4
        assert cfg.hamiltonian is not None
        assert cfg.hamiltonian.number_off_diag_terms > 0


@require_data
@pytest.mark.slow
def test_square_5x5_engine_vs_host(rng):
    """square_5x5 (N=5.2M, 50 bonds) — the largest config whose host
    matvec is still test-tractable; with this the automated matrix covers
    every `make check` config (Makefile:111-125) plus two sizes beyond."""
    cfg = _load("heisenberg_square_5x5.yaml")
    x = _random_x(cfg, rng)
    eng = LocalEngine(cfg.hamiltonian)
    np.testing.assert_allclose(
        np.asarray(eng.matvec(x)), cfg.hamiltonian.matvec_host(x),
        atol=ATOL, rtol=RTOL)


@require_data
@pytest.mark.slow
def test_chain_28_fused_vs_independent(rng):
    """chain_28 (N=40.1M) — fused (recompute-on-the-fly) engine against
    the term-compiler-independent bit-op apply; host matvec_host is too
    slow at this size, the independent ring apply is not."""
    from independent_ref import heisenberg_ring_apply

    cfg = _load("heisenberg_chain_28.yaml")
    x = _random_x(cfg, rng)
    eng = LocalEngine(cfg.hamiltonian, mode="fused")
    y_ref = heisenberg_ring_apply(cfg.basis.representatives, 28, x)
    np.testing.assert_allclose(
        np.asarray(eng.matvec(x)), y_ref, atol=ATOL, rtol=RTOL)
