"""Continuous-profiling plane (obs/hlo.py + obs/profile.py, DESIGN.md
§32): HLO cost attribution at compile, sampled trace windows with the
measured-overhead guard, triggered deep capture, and differential
profiling.

Unit tests fake ``jax.profiler.trace`` where only the plumbing is under
test (capture cadence, overhead ledger, latch); the real profiler — and
the real <2% overhead acceptance — is exercised by ``make
profile-check`` (tools/profile_check.py), and the 2-process artifact
agreement by the DMT_MH_PROF worker leg here.
"""

import contextlib
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.obs import hlo as H
from distributed_matvec_tpu.obs import profile as P
from distributed_matvec_tpu.utils.config import update_config

from test_operator import build_heisenberg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_obs():
    obs.reset_all()
    yield
    obs.reset_all()


# a small synthetic optimized-HLO module covering every phase bucket
SYNTH_HLO = """\
HloModule synth, entry_computation_layout={(f64[64]{0})->f64[64]{0}}

ENTRY %main (x: f64[64]) -> f64[64] {
  %x = f64[64]{0} parameter(0)
  %c = f64[] constant(2)
  %fused = f64[128]{0} fusion(%x), kind=kLoop, metadata={op_name="jit(apply)/gather"}
  %perm = f64[128]{0} collective-permute(%fused), metadata={op_name="jit(apply)/ppermute"}
  %dotp = f64[64]{0} dot(%fused, %fused), metadata={op_name="jit(apply)/dot_general"}
  %scat = f64[64]{0} scatter(%dotp, %perm), metadata={op_name="jit(apply)/scatter-add"}
  ROOT %out = f64[64]{0} add(%scat, %dotp)
}
"""


def _totals(byts=1.0e6, flops=3.0e5):
    return {"bytes": byts, "flops": flops, "transcendentals": 0.0}


# ---------------------------------------------------------------------------
# attribution (pure)


def test_classify_and_parse_synthetic_hlo():
    ops = {o["name"]: o for o in H.parse_hlo_ops(SYNTH_HLO)}
    assert ops["x"]["phase"] == "plan_h2d"
    assert ops["c"]["phase"] == "overhead"
    assert ops["perm"]["phase"] == "exchange"
    assert ops["scat"]["phase"] == "accumulate"
    assert ops["dotp"]["phase"] == "compute"
    assert ops["fused"]["phase"] == "compute"   # gather: no refinement
    assert ops["dotp"]["shape_bytes"] == 64 * 8
    # op_name metadata refines a compute-bucketed fusion
    assert H.classify_op("fusion", "jit(f)/ppermute/foo") == "exchange"
    assert H.classify_op("fusion", "jit(f)/segment_sum") == "accumulate"
    assert H.classify_op("weird-new-opcode") == "compute"


def test_phase_buckets_sum_to_program_totals_exactly():
    att = H.attribute_costs(SYNTH_HLO, _totals())
    for axis in ("bytes", "flops"):
        assert sum(r[axis] for r in att["phases"].values()) \
            == pytest.approx(_totals()[axis], abs=0.5)
        assert sum(o[axis] for o in att["ops"]) \
            == pytest.approx(_totals()[axis], abs=0.5)
    # flops only land on flop-capable opcodes (never on parameter/copy)
    per_op = {o["name"]: o for o in att["ops"]}
    assert per_op["x"]["flops"] == 0.0
    assert per_op["perm"]["flops"] == 0.0
    assert per_op["dotp"]["flops"] > 0.0


def test_profile_fingerprint_is_content_address():
    p1 = H.build_profile("k", SYNTH_HLO, _totals(), program="prog")
    p2 = H.build_profile("k2", SYNTH_HLO, _totals(2e6), program="prog")
    assert p1["fingerprint"] == p2["fingerprint"]     # same program text
    p3 = H.build_profile("k", SYNTH_HLO + "\n// x", _totals())
    assert p3["fingerprint"] != p1["fingerprint"]     # any change re-keys


# ---------------------------------------------------------------------------
# compile-time recording + artifact round-trip


def test_record_executable_costs_roundtrip(clean_obs, tmp_path,
                                           monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    ex = jax.jit(lambda a, b: a @ b + b).lower(
        jnp.ones((16, 16)), jnp.ones((16, 16))).compile()
    prof = H.record_executable_costs("k@1", ex, program="unit_prog")
    assert prof is not None and prof["program"] == "unit_prog"
    t = prof["totals"]
    assert t["bytes"] > 0
    assert sum(r["bytes"] for r in prof["phases"].values()) \
        == pytest.approx(t["bytes"], abs=0.5)
    # content-addressed artifact next to the XLA cache, round-tripping
    art = prof["artifact"]
    fp = prof["fingerprint"]
    assert art.endswith(os.path.join("hlo-profile", fp[:2], fp + ".json"))
    assert H.load_profile(art)["totals"] == t
    # registry + event + counter
    assert H.executable_costs()["k@1"] == prof
    ev = obs.events("hlo_cost")[-1]
    assert ev["program"] == "unit_prog" and ev["fingerprint"] == fp
    assert ev["phase_bytes_compute"] >= 0
    assert obs.snapshot()["counters"][
        "hlo_profile_count{program=unit_prog}"] == 1
    # a DIFFERENT program content-addresses to a DIFFERENT artifact
    ex2 = jax.jit(lambda a, b: a @ b - 2.0 * b).lower(
        jnp.ones((16, 16)), jnp.ones((16, 16))).compile()
    prof2 = H.record_executable_costs("k@2", ex2, program="unit_prog2")
    assert prof2["fingerprint"] != fp
    assert prof2["artifact"] != art


def test_record_costs_obs_off_noop(clean_obs, monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("DMT_OBS", "off")
    ex = jax.jit(lambda a: a + 1.0).lower(jnp.ones(8)).compile()
    assert H.record_executable_costs("k@off", ex) is None
    assert H.executable_costs() == {}


# ---------------------------------------------------------------------------
# sampled windows: cadence, ledger, latch, off-mode no-op


@contextlib.contextmanager
def _fake_trace(calls, fail=False, cost_s=0.0):
    """Stand-in for jax.profiler.trace: records targets, optionally
    burns time on entry (to exercise the overhead guard) or refuses."""
    import jax

    class _Trace:
        def __init__(self, target):
            if fail:
                raise RuntimeError("profiler unavailable")
            calls.append(target)

        def __enter__(self):
            if cost_s:
                import time
                time.sleep(cost_s)
            return self

        def __exit__(self, *a):
            return False

    orig = jax.profiler.trace
    jax.profiler.trace = _Trace
    try:
        yield
    finally:
        jax.profiler.trace = orig


def test_sample_window_off_mode_is_noop(clean_obs, monkeypatch,
                                        tmp_path):
    monkeypatch.setenv("DMT_OBS", "off")
    monkeypatch.setenv("DMT_PROFILE", "sampled")   # obs off wins
    assert P.profile_mode() == "off"
    with P.sample_window("local", 64) as captured:
        pass
    assert captured is False
    assert P.overhead_snapshot()["applies"] == 0   # no ledger, provable
    assert P.profile_due(64) is False
    assert P.trigger_capture("anything") is None


def test_sample_window_cadence_and_capture(clean_obs, monkeypatch,
                                           tmp_path):
    monkeypatch.setenv("DMT_OBS_DIR", str(tmp_path / "run"))
    monkeypatch.setenv("DMT_PROFILE", "sampled")
    obs.reset()                       # re-point the sink
    update_config(profile_every=4)
    assert not P.profile_due(0)       # apply 0 pays compile
    assert not P.profile_due(3)
    assert P.profile_due(4) and P.profile_due(8)
    calls = []
    with _fake_trace(calls):
        for idx in range(9):
            with P.sample_window("local", idx) as captured:
                pass
            assert captured == (idx in (4, 8))
    snap = P.overhead_snapshot()
    assert snap["applies"] == 9 and snap["profiled"] == 2
    assert len(calls) == 2 and calls[0].endswith("local-apply4")
    # captured dirs are stamped with their identity
    meta = json.load(open(os.path.join(calls[-1], "PROFILE_META.json")))
    assert meta["capture"] == "sampled" and meta["engine"] == "local"
    assert meta["apply"] == 8
    evs = [e for e in obs.events("profile_captured")
           if e.get("capture") == "sampled"]
    assert [e["apply"] for e in evs] == [4, 8]
    assert snap["last_dir"] == calls[-1]
    # a refused trace start degrades to an unprofiled apply, no event
    with _fake_trace(calls, fail=True):
        with P.sample_window("local", 12) as captured:
            pass
    assert captured is False
    assert P.overhead_snapshot()["profiled"] == 2


def test_overhead_guard_latches_and_says_so(clean_obs, monkeypatch,
                                            tmp_path):
    monkeypatch.setenv("DMT_OBS_DIR", str(tmp_path / "run"))
    monkeypatch.setenv("DMT_PROFILE", "sampled")
    obs.reset()
    update_config(profile_every=2, profile_overhead_pct=1.0)
    calls = []
    with _fake_trace(calls, cost_s=0.004):   # 4 ms burned per capture
        for idx in range(5):
            with P.sample_window("local", idx):
                pass
    assert P.overhead_latched()
    assert P.measured_overhead_pct() > 1.0
    assert not P.profile_due(6)              # latched: sampling stays off
    ev = obs.events("profile_overhead_latch")[-1]
    assert ev["budget_pct"] == 1.0 and ev["overhead_pct"] > 1.0
    assert obs.snapshot()["counters"]["profile_overhead_latch_count"] == 1
    update_config(profile_overhead_pct=2.0)  # restore the default
    P.reset_profile()
    assert not P.overhead_latched()


# ---------------------------------------------------------------------------
# triggered deep capture


def test_triggered_capture_on_slo_burn(clean_obs, monkeypatch, tmp_path):
    from distributed_matvec_tpu.obs.slo import SloSpec

    monkeypatch.setenv("DMT_OBS_DIR", str(tmp_path / "run"))
    monkeypatch.setenv("DMT_PROFILE", "triggered")
    obs.reset()
    spec = SloSpec("steady_apply_ms", kind="matvec_apply",
                   field="wall_ms", target=10.0)
    bad = [{"kind": "matvec_apply", "ts": 1000.0 + i, "wall_ms": 100.0}
           for i in range(10)]
    obs.check_slos([spec], events=bad)       # ok -> firing: triggers
    caps = [e for e in obs.events("profile_captured")
            if e.get("capture") == "triggered"]
    assert len(caps) == 1
    bundle = caps[0]["bundle"]
    assert os.path.exists(bundle)
    assert "profile_slo_burn_steady_apply_ms" in os.path.basename(bundle)
    payload = json.load(open(bundle))
    assert "overhead" in payload["profile"]
    assert payload["slo"] == "steady_apply_ms"
    # steady firing does not re-trigger (one bundle per reason)
    obs.check_slos([spec], events=bad)
    assert len([e for e in obs.events("profile_captured")
                if e.get("capture") == "triggered"]) == 1


def test_trigger_capture_sanitizes_reason_and_snapshots_hlo(
        clean_obs, monkeypatch, tmp_path):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("DMT_OBS_DIR", str(tmp_path / "run"))
    monkeypatch.setenv("DMT_PROFILE", "sampled")
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "off")
    obs.reset()
    ex = jax.jit(lambda a: a * 2.0).lower(jnp.ones(8)).compile()
    H.record_executable_costs("k@t", ex, program="trig_prog")
    path = P.trigger_capture("trend gate: cfg/x regressed!",
                             regressions=[{"metric": "device_ms"}])
    assert path and os.path.exists(path)
    assert "profile_trend_gate_cfg_x_regressed" in os.path.basename(path)
    payload = json.load(open(path))
    hot = payload["profile"]["hlo"]
    assert any(p["program"] == "trig_prog" and p["top_ops"] for p in hot)
    assert payload["regressions"] == [{"metric": "device_ms"}]


# ---------------------------------------------------------------------------
# differential profiling


def test_diff_names_regressed_op_and_direction():
    base = H.build_profile("k", SYNTH_HLO, _totals(), program="p")
    worse = json.loads(json.dumps(base))
    victim = max(worse["ops"], key=lambda o: o["bytes"])
    victim["bytes"] *= 10.0
    d = H.diff_profiles(base, worse, threshold=0.25)
    assert d["regressions"]
    assert d["regressions"][0]["name"] == victim["name"]
    assert d["regressions"][0]["axis"] == "bytes"
    assert d["same_program"] is True
    # direction-aware: the same 10x change in the OTHER direction is an
    # improvement, not a regression
    d_rev = H.diff_profiles(worse, base, threshold=0.25)
    assert d_rev["regressions"] == []
    # renamed-but-identical ops still align via opcode#ordinal
    renamed = json.loads(json.dumps(base))
    for o in renamed["ops"]:
        o["name"] = "renamed." + o["name"]
    d_ren = H.diff_profiles(base, renamed, threshold=0.25)
    assert d_ren["regressions"] == [] and d_ren["appeared"] == []


def test_profile_diff_cli_and_obs_report_profile(tmp_path):
    base = H.build_profile("k", SYNTH_HLO, _totals(), program="p")
    bpath = tmp_path / "base.json"
    bpath.write_text(json.dumps(base))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profile_diff.py"),
         str(bpath), str(bpath)], capture_output=True, text=True)
    assert r.returncode == 0 and "no per-op regression" in r.stdout, \
        r.stdout + r.stderr
    worse = json.loads(json.dumps(base))
    victim = max(worse["ops"], key=lambda o: o["bytes"])
    victim["bytes"] *= 10.0
    wpath = tmp_path / "worse.json"
    wpath.write_text(json.dumps(worse))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profile_diff.py"),
         str(bpath), str(wpath)], capture_output=True, text=True)
    assert r.returncode == 1 and "REGRESSION" in r.stdout, r.stdout
    assert victim["name"] in r.stdout
    # obs_report renders a single artifact (exit 0) and a run with no
    # profile exits 2
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "profile", str(bpath)], capture_output=True, text=True)
    assert r.returncode == 0 and "hottest ops" in r.stdout, \
        r.stdout + r.stderr
    empty = tmp_path / "empty_run"
    (empty / "rank_0").mkdir(parents=True)
    (empty / "rank_0" / "events.jsonl").write_text("")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "profile", str(empty)], capture_output=True, text=True)
    assert r.returncode == 2


def test_bench_trend_gates_profile_metrics():
    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join(REPO, "tools", "bench_trend.py"))
    bt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bt)
    assert "profile_overhead_pct" in bt.DEFAULT_GATE
    for m in ("hlo_flops", "hlo_bytes", "profile_overhead_pct"):
        assert m in bt.METRIC_WHITELIST
    from distributed_matvec_tpu.obs.directions import is_higher_better
    assert not is_higher_better("hlo_bytes")
    assert not is_higher_better("hlo_flops")
    assert not is_higher_better("profile_overhead_pct")


# ---------------------------------------------------------------------------
# live reconciliation: hlo third column vs measured apply walls


def test_roofline_hlo_column_reconciles(clean_obs, monkeypatch, tmp_path):
    import jax

    from distributed_matvec_tpu.obs import roofline as R
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    monkeypatch.setenv("DMT_OBS_DIR", str(tmp_path / "run"))
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "off")
    obs.reset()
    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    n = op.basis.number_states
    x = np.random.default_rng(3).standard_normal(n)
    eng.apply_memory_analysis(x)      # records the apply's cost profile
    for _ in range(4):
        y = eng.matvec(x)
    jax.block_until_ready(y)
    obs.flush()
    rep = R.roofline_report(obs.events())
    grp = rep["groups"]["local/ell"]
    assert grp["hlo"]["program"] == "local_ell_apply"
    hlo_sum = sum(float(a.get("hlo_ms") or 0.0)
                  for a in grp["phases"].values())
    wall = float(grp["wall_ms"])
    # the documented tolerance: Σ hlo_ms is normalized to the measured
    # wall; only 4-decimal rounding across the buckets can separate them
    assert hlo_sum == pytest.approx(wall, rel=0.02)
    assert any((a.get("hlo_ms") or 0.0) > 0.0
               for a in grp["phases"].values())


# ---------------------------------------------------------------------------
# 2-process agreement


def test_multihost_profile_ranks_agree(tmp_path):
    """A REAL 2-process run (DMT_MH_PROF leg): both ranks record the
    same rank-local apply program's cost profile and must agree on its
    fingerprint, totals, and content-addressed artifact name."""
    import socket

    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    run = tmp_path / "prof_run"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["DMT_MH_PROF"] = "1"
    env["DMT_OBS_DIR"] = str(run)
    env["DMT_ARTIFACT_DIR"] = str(tmp_path / "art")
    env["DMT_ARTIFACT_CACHE"] = "on"   # conftest turns it off globally
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    lines = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]
        l, = [ln for ln in out.splitlines()
              if ln.startswith(f"[p{pid}] PROF_OK ")]
        lines.append(l.split()[2:])          # [fp, flops, bytes, artifact]
    assert lines[0] == lines[1], lines       # ranks agree, per-field
    # both ranks resolved the SAME content-addressed artifact, and the
    # shared root holds exactly that one profile for the apply program
    fp, _, _, artname = lines[0]
    assert artname == fp + ".json"
    art = tmp_path / "art" / "hlo-profile" / fp[:2] / artname
    assert art.exists()
    assert H.load_profile(str(art))["program"] == "distributed_ell_apply"
