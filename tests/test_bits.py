"""Device bit-op kernels vs host references: hashing and basis lookup."""

import numpy as np
import pytest

from distributed_matvec_tpu.enumeration.host import hash64 as hash64_host
from distributed_matvec_tpu.ops.bits import (build_sorted_lookup, hash64,
                                             state_index_bucketed,
                                             state_index_sorted)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def test_hash64_matches_host(rng):
    x = rng.integers(0, np.iinfo(np.int64).max, 1000).astype(np.uint64)
    np.testing.assert_array_equal(np.asarray(hash64(x)), hash64_host(x))


@pytest.mark.parametrize("n_bits,n", [(16, 100), (32, 5000), (40, 317)])
def test_bucketed_lookup_matches_searchsorted(n_bits, n, rng):
    lim = np.uint64(1) << np.uint64(n_bits)
    reps = np.sort(rng.choice(
        np.arange(0, int(lim), max(int(lim) // (4 * n), 1), dtype=np.uint64),
        n, replace=False))
    # queries: hits, near-misses, extremes, and out-of-range garbage
    queries = np.concatenate([
        rng.choice(reps, n // 2),
        rng.choice(reps, n // 2) ^ np.uint64(1),
        np.array([0, int(lim) - 1, np.iinfo(np.uint64).max >> 1],
                 np.uint64),
        np.array([np.uint64(0xFFFFFFFFFFFFFFFF)]),
    ]).astype(np.uint64)

    pair, dir_tab, shift, probes = build_sorted_lookup(reps, n_bits)
    idx_b, found_b = (np.asarray(a) for a in state_index_bucketed(
        pair, dir_tab, queries, shift=shift, probes=probes))
    idx_s, found_s = (np.asarray(a) for a in state_index_sorted(
        reps, queries))

    ref_found = np.isin(queries, reps)
    np.testing.assert_array_equal(found_b, ref_found)
    np.testing.assert_array_equal(found_s, ref_found)
    np.testing.assert_array_equal(idx_b[ref_found], idx_s[ref_found])
    assert (reps[idx_b[ref_found]] == queries[ref_found]).all()


def test_bucketed_lookup_single_entry():
    reps = np.array([42], np.uint64)
    pair, dir_tab, shift, probes = build_sorted_lookup(reps, 8)
    q = np.array([0, 42, 43, 255], np.uint64)
    idx, found = (np.asarray(a) for a in state_index_bucketed(
        pair, dir_tab, q, shift=shift, probes=probes))
    np.testing.assert_array_equal(found, [False, True, False, False])
    assert idx[1] == 0


def test_native_lookup_owners_matches_numpy():
    """dmt_lookup_owners (threaded hash + per-shard binary search) must be
    bit-identical to the NumPy owner/searchsorted path, including misses."""
    import numpy as np
    import pytest

    from distributed_matvec_tpu.enumeration.native import (lookup_owners,
                                                           native_available)
    from distributed_matvec_tpu.enumeration.host import shard_index

    if not native_available():
        pytest.skip("native kernel unavailable")
    rng = np.random.default_rng(11)
    D, M = 8, 512
    # per-shard sorted prefixes with SENTINEL padding
    SENT = np.uint64(0xFFFFFFFFFFFFFFFF)
    counts = rng.integers(1, M, size=D)
    pool = np.sort(rng.choice(1 << 30, size=4096, replace=False)
                   .astype(np.uint64))
    owner_pool = shard_index(pool, D)
    alphas = np.full((D, M), SENT, np.uint64)
    for d in range(D):
        mine = pool[owner_pool == d][: counts[d]]
        counts[d] = mine.size
        alphas[d, : mine.size] = mine

    # queries: half present, half absent (but hashed to some shard)
    present = pool[rng.integers(0, pool.size, 3000)]
    absent = rng.choice(1 << 30, size=3000).astype(np.uint64)
    betas = np.concatenate([present, absent])
    rng.shuffle(betas)

    owner, idx, found = lookup_owners(betas, alphas, counts)
    np.testing.assert_array_equal(owner, shard_index(betas, D))
    for i in range(betas.size):
        d = owner[i]
        ip = np.searchsorted(alphas[d, : counts[d]], betas[i])
        ok = ip < counts[d] and alphas[d, ip] == betas[i]
        assert found[i] == ok
        if ok:
            assert idx[i] == ip
