"""Device bit-op kernels vs host references: hashing and basis lookup."""

import numpy as np
import pytest

from distributed_matvec_tpu.enumeration.host import hash64 as hash64_host
from distributed_matvec_tpu.ops.bits import (build_sorted_lookup, hash64,
                                             state_index_bucketed,
                                             state_index_sorted)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def test_hash64_matches_host(rng):
    x = rng.integers(0, np.iinfo(np.int64).max, 1000).astype(np.uint64)
    np.testing.assert_array_equal(np.asarray(hash64(x)), hash64_host(x))


@pytest.mark.parametrize("n_bits,n", [(16, 100), (32, 5000), (40, 317)])
def test_bucketed_lookup_matches_searchsorted(n_bits, n, rng):
    lim = np.uint64(1) << np.uint64(n_bits)
    reps = np.sort(rng.choice(
        np.arange(0, int(lim), max(int(lim) // (4 * n), 1), dtype=np.uint64),
        n, replace=False))
    # queries: hits, near-misses, extremes, and out-of-range garbage
    queries = np.concatenate([
        rng.choice(reps, n // 2),
        rng.choice(reps, n // 2) ^ np.uint64(1),
        np.array([0, int(lim) - 1, np.iinfo(np.uint64).max >> 1],
                 np.uint64),
        np.array([np.uint64(0xFFFFFFFFFFFFFFFF)]),
    ]).astype(np.uint64)

    pair, dir_tab, shift, probes = build_sorted_lookup(reps, n_bits)
    idx_b, found_b = (np.asarray(a) for a in state_index_bucketed(
        pair, dir_tab, queries, shift=shift, probes=probes))
    idx_s, found_s = (np.asarray(a) for a in state_index_sorted(
        reps, queries))

    ref_found = np.isin(queries, reps)
    np.testing.assert_array_equal(found_b, ref_found)
    np.testing.assert_array_equal(found_s, ref_found)
    np.testing.assert_array_equal(idx_b[ref_found], idx_s[ref_found])
    assert (reps[idx_b[ref_found]] == queries[ref_found]).all()


def test_bucketed_lookup_single_entry():
    reps = np.array([42], np.uint64)
    pair, dir_tab, shift, probes = build_sorted_lookup(reps, 8)
    q = np.array([0, 42, 43, 255], np.uint64)
    idx, found = (np.asarray(a) for a in state_index_bucketed(
        pair, dir_tab, q, shift=shift, probes=probes))
    np.testing.assert_array_equal(found, [False, True, False, False])
    assert idx[1] == 0
