"""Dynamics solvers vs the independent dense reference (DESIGN.md §29).

KPM moments/DOS against the dense projected matrix's own Chebyshev
recurrence and exact spectrum (broadening-aware: both sides carry the
same Jackson kernel), Krylov ``exp(-iHt)`` against dense ``expm`` at
rtol 1e-10, thick-restart block Lanczos against the full-memory solve
at rtol 1e-12 with the workspace provably bounded, observables against
dense expectation values, checkpoint/resume bit-consistency, the serve
layer's dynamics job kinds, and a REAL 2-process rank-local-mesh leg.
"""

import os
import sys

import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.parallel.engine import LocalEngine
from distributed_matvec_tpu.solve import (jackson_kernel, kpm_dos,
                                          kpm_moments,
                                          kpm_spectral_function,
                                          krylov_evolve, lanczos,
                                          lanczos_block, lorentz_kernel,
                                          reconstruct_dos, spectral_bounds)
from distributed_matvec_tpu.solve.lanczos import _rand_like

from test_operator import build_heisenberg, dense_effective_matrix

SYMS_12 = [([*range(1, 12), 0], 0), ([*reversed(range(12))], 0)]


@pytest.fixture(scope="module")
def chain12():
    """chain_12 symmetric sector: (op, dense H, LocalEngine)."""
    op = build_heisenberg(12, 6, 1, SYMS_12)
    op.basis.build()
    h = dense_effective_matrix(op).real
    return op, h, LocalEngine(op)


@pytest.fixture(scope="module")
def chain12_streamed(chain12):
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine
    op, _, _ = chain12
    return DistributedEngine(op, n_devices=1, mode="streamed")


def _dense_moments_same_vectors(h, scale, V0, n_moments):
    """The reference Chebyshev recurrence on the dense matrix, SAME
    start block — shares no algebra with solve/kpm.py's engine loop."""
    a, b = scale
    Ht = (h - b * np.eye(h.shape[0])) / a
    t0, t1 = V0, Ht @ V0
    mu = np.zeros((n_moments, V0.shape[1]))
    mu[0] = (t0 * t0).sum(0)
    mu[1] = (t0 * t1).sum(0)
    j, filled = 1, 2
    while filled < n_moments:
        if 2 * j - 1 >= filled:
            mu[2 * j - 1] = 2 * (t1 * t0).sum(0) - mu[1]
            filled += 1
        if 2 * j < n_moments and 2 * j >= filled:
            mu[2 * j] = 2 * (t1 * t1).sum(0) - mu[0]
            filled += 1
        if filled < n_moments:
            t0, t1 = t1, 2 * Ht @ t1 - t0
            j += 1
    return mu.mean(axis=1)


# ---------------------------------------------------------------------------
# spectral bounds


def test_spectral_bounds_bracket(chain12):
    op, h, eng = chain12
    w = np.linalg.eigvalsh(h)
    lo, hi, napply = spectral_bounds(eng.matvec, n=op.basis.number_states,
                                     iters=48, seed=3)
    assert lo < w[0] and hi > w[-1], (lo, hi, w[0], w[-1])
    # the margin must not be absurd: the bracket stays within 25% of
    # the true span on each end
    span = w[-1] - w[0]
    assert lo > w[0] - 0.25 * span and hi < w[-1] + 0.25 * span
    assert napply == 48


# ---------------------------------------------------------------------------
# KPM


def test_kpm_moments_match_dense_recurrence(chain12):
    """Engine moments == dense-matrix moments on the SAME seeded start
    block, to recurrence precision."""
    op, h, eng = chain12
    n = op.basis.number_states
    res = kpm_moments(eng.matvec, n_moments=64, n=n, n_vectors=3, seed=2)
    V0 = _rand_like((n, 3), np.float64, 2)
    V0 = V0 / np.linalg.norm(V0, axis=0, keepdims=True)
    mu_ref = _dense_moments_same_vectors(h, res.scale, V0, 64)
    np.testing.assert_allclose(res.moments, mu_ref, rtol=0, atol=1e-12)
    assert res.moments[0] == 1.0
    # doubling: ~n_moments/2 recurrence applies plus the bounds pass
    assert res.num_applies <= 64 // 2 + 64 + 2


def test_kpm_dos_matches_dense_spectrum_within_broadening(chain12):
    """Broadening-aware DOS check: the stochastic-trace KPM density vs
    the EXACT spectrum pushed through the SAME Jackson kernel — the
    residual is stochastic-trace noise ~ sqrt(2/(N R)), not resolution
    mismatch."""
    op, h, eng = chain12
    n = op.basis.number_states
    w = np.linalg.eigvalsh(h)
    energies, rho, res = kpm_dos(eng.matvec, n_moments=96, n=n,
                                 n_vectors=6, seed=4)
    a, b = res.scale
    ang = np.arccos(np.clip((w - b) / a, -1.0, 1.0))
    mu_exact = np.array([np.mean(np.cos(k * ang)) for k in range(96)])
    _, rho_ref = reconstruct_dos(mu_exact, res.scale, npoints=512)
    rel = np.linalg.norm(rho - rho_ref) / np.linalg.norm(rho_ref)
    assert rel < 0.35, rel
    # Jackson-kernel DOS is strictly positive and integrates to ~1
    assert rho.min() > -1e-12
    mass = np.trapezoid(rho, energies) if hasattr(np, "trapezoid") \
        else np.trapz(rho, energies)
    assert abs(mass - 1.0) < 0.02, mass


def test_kpm_streamed_matches_local_same_block(chain12, chain12_streamed):
    """A streamed engine's moment series equals the local engine's on
    the same global start block — and its plan is built ONCE for the
    whole run (engine_init counted once)."""
    import jax.numpy as jnp
    op, _, eng_l = chain12
    eng = chain12_streamed
    n = op.basis.number_states
    V0 = _rand_like((n, 2), np.float64, 11)
    V0 = V0 / np.linalg.norm(V0, axis=0, keepdims=True)
    V0h = jnp.stack([eng.to_hashed(V0[:, i]) for i in range(2)], axis=-1)
    obs.reset()
    bounds = (-24.0, 14.0)
    r_s = kpm_moments(eng.matvec, n_moments=32, V0=V0h, bounds=bounds)
    r_l = kpm_moments(eng_l.matvec, n_moments=32, V0=jnp.asarray(V0),
                      bounds=bounds)
    np.testing.assert_allclose(r_s.moments, r_l.moments, rtol=0,
                               atol=1e-12)
    # the warm engine is reused across every moment apply: zero NEW
    # engine builds inside the solve
    assert len([e for e in obs.events("engine_init")]) == 0


def test_kpm_kernels_and_reconstruction():
    g_j = jackson_kernel(64)
    assert g_j[0] == pytest.approx(1.0)
    assert np.all(np.diff(g_j) < 0) and g_j[-1] > 0
    g_l = lorentz_kernel(64)
    assert g_l[0] == pytest.approx(1.0) and np.all(g_l > 0)
    with pytest.raises(ValueError):
        from distributed_matvec_tpu.solve.kpm import _kernel
        _kernel("gauss", 8, 4.0)
    # a pure point mass at x=0.3 reconstructs to a peak near E = a*0.3+b
    mu = np.cos(np.arange(128) * np.arccos(0.3))
    E, rho = reconstruct_dos(mu, (2.0, 1.0), npoints=1024)
    assert abs(E[np.argmax(rho)] - (2.0 * 0.3 + 1.0)) < 0.05


def test_kpm_spectral_function_weight(chain12):
    """S(E) carries ||O psi||^2 of spectral weight; O = H makes the
    integral computable against the dense reference."""
    op, h, eng = chain12
    n = op.basis.number_states
    psi = _rand_like((n,), np.float64, 5)
    psi /= np.linalg.norm(psi)
    import jax.numpy as jnp
    E, S, res, w2 = kpm_spectral_function(
        eng.matvec, jnp.asarray(psi), eng.matvec, n_moments=64)
    want_w2 = float(psi @ (h @ (h @ psi)))
    assert w2 == pytest.approx(want_w2, rel=1e-10)
    mass = np.trapezoid(S, E) if hasattr(np, "trapezoid") \
        else np.trapz(S, E)
    assert mass == pytest.approx(w2, rel=0.05)


def test_kpm_checkpoint_resume_bit_consistent(chain12, tmp_path):
    op, _, eng = chain12
    n = op.basis.number_states
    ck = str(tmp_path / "kpm_ck.h5")
    full = kpm_moments(eng.matvec, n_moments=40, n=n, n_vectors=2,
                       seed=5)
    part = kpm_moments(eng.matvec, n_moments=40, n=n, n_vectors=2,
                       seed=5, checkpoint_path=ck, checkpoint_every=4)
    resumed = kpm_moments(eng.matvec, n_moments=40, n=n, n_vectors=2,
                          seed=5, checkpoint_path=ck, checkpoint_every=4)
    assert resumed.resumed_from > 0
    # the resumed series must equal BOTH the checkpointing run it
    # restored from and a checkpoint-free run, bit for bit
    assert np.array_equal(part.moments, full.moments)
    assert np.array_equal(resumed.moments, full.moments)


def test_kpm_refuses_pair_engines():
    class FakePair:
        pair = True

        def matvec(self, x):
            return x
    with pytest.raises(ValueError, match="pair-mode"):
        kpm_moments(FakePair().matvec, n_moments=8, n=4)


# ---------------------------------------------------------------------------
# Krylov time evolution


def test_evolve_matches_dense_expm(chain12):
    from scipy.linalg import expm
    op, h, eng = chain12
    n = op.basis.number_states
    psi0 = _rand_like((n,), np.float64, 7)
    psi0 /= np.linalg.norm(psi0)
    res = krylov_evolve(eng.matvec, psi0=psi0, t_final=2.0, tol=1e-12,
                        krylov_dim=20)
    ref = expm(-2.0j * h) @ psi0
    np.testing.assert_allclose(np.asarray(res.psi), ref, rtol=0,
                               atol=1e-10 * np.abs(ref).max())
    assert res.times[-1] == pytest.approx(2.0)
    assert len(res.times) == len(res.energies)


def test_evolve_unitarity_and_energy_drift(chain12):
    op, h, eng = chain12
    n = op.basis.number_states
    res = krylov_evolve(eng.matvec, n=n, t_final=3.0, tol=1e-12,
                        krylov_dim=20, seed=1)
    # the acceptance bound make dynamics-check gates: < 1e-12 PER STEP
    assert res.norm_drift < 1e-12 * max(res.num_steps, 1)
    assert res.energy_drift < 1e-11


def test_evolve_streamed_multi_rhs_path(chain12, chain12_streamed):
    """exp(-iHt) on a STREAMED engine (complex state as the 2-column
    real block through the multi-RHS apply) matches dense expm; the
    plan is reused across the whole trajectory."""
    from scipy.linalg import expm
    op, h, _ = chain12
    eng = chain12_streamed
    n = op.basis.number_states
    psi0 = _rand_like((n,), np.float64, 9)
    psi0 /= np.linalg.norm(psi0)
    obs.reset()
    res = krylov_evolve(eng.matvec, psi0=eng.to_hashed(psi0),
                        t_final=1.0, tol=1e-12, krylov_dim=16)
    assert len([e for e in obs.events("engine_init")]) == 0
    ref = expm(-1.0j * h) @ psi0
    got = eng.from_hashed(np.asarray(res.psi))
    np.testing.assert_allclose(got, ref, rtol=0,
                               atol=1e-10 * np.abs(ref).max())


def test_evolve_complex_sector_native(rng):
    from scipy.linalg import expm
    op = build_heisenberg(8, 4, None, [([*range(1, 8), 0], 1)])
    op.basis.build()
    h = dense_effective_matrix(op)
    eng = LocalEngine(op)
    n = op.basis.number_states
    psi0 = _rand_like((n,), np.complex128, 3)
    psi0 /= np.linalg.norm(psi0)
    res = krylov_evolve(eng.matvec, psi0=psi0, t_final=1.0, tol=1e-12,
                        krylov_dim=16)
    ref = expm(-1.0j * h) @ psi0
    np.testing.assert_allclose(np.asarray(res.psi), ref, rtol=0,
                               atol=1e-10)


def test_evolve_checkpoint_resume_bit_consistent(chain12, tmp_path):
    op, _, eng = chain12
    n = op.basis.number_states
    psi0 = _rand_like((n,), np.float64, 13)
    psi0 /= np.linalg.norm(psi0)
    ck = str(tmp_path / "ev_ck.h5")
    kw = dict(t_final=2.0, tol=1e-12, krylov_dim=16)
    part = krylov_evolve(eng.matvec, psi0=psi0, max_steps=3,
                         checkpoint_path=ck, checkpoint_every=1, **kw)
    assert part.num_steps == 3 and part.times[-1] < 2.0
    done = krylov_evolve(eng.matvec, psi0=psi0, checkpoint_path=ck, **kw)
    solo = krylov_evolve(eng.matvec, psi0=psi0, **kw)
    assert done.resumed_from == 3
    # BIT-consistent with the uninterrupted trajectory (the §29
    # acceptance): same accepted steps, same state bits
    assert np.array_equal(done.times, solo.times)
    assert np.array_equal(np.asarray(done.psi), np.asarray(solo.psi))
    assert np.array_equal(done.energies, solo.energies)


def test_evolve_observable_trajectory(chain12):
    from distributed_matvec_tpu.models.observables import bind_observables
    op, h, eng = chain12
    n = op.basis.number_states
    bo = bind_observables([op], eng)     # H as the (commuting) observable
    res = krylov_evolve(eng.matvec, n=n, t_final=1.0, tol=1e-12,
                        krylov_dim=16, seed=2, observables=bo)
    series = res.observables[bo[0].name]
    assert len(series) == res.num_steps + 1
    vals = np.array([v for _, v in series])
    # <H> is conserved under exp(-iHt)
    np.testing.assert_allclose(vals, vals[0], rtol=0, atol=1e-10)
    np.testing.assert_allclose(vals[0], res.energies[0], rtol=1e-12)


# ---------------------------------------------------------------------------
# thick-restart lanczos_block


def test_thick_restart_parity_and_bounded_workspace(chain12):
    op, h, eng = chain12
    n = op.basis.number_states
    w = np.linalg.eigvalsh(h)
    obs.reset()
    full = lanczos_block(eng.matvec, n=n, k=2, tol=1e-13, max_iters=300,
                         seed=3, compute_eigenvectors=True)
    thick = lanczos_block(eng.matvec, n=n, k=2, tol=1e-13, max_iters=600,
                          seed=3, max_basis_size=16,
                          compute_eigenvectors=True)
    assert thick.converged and thick.restarts > 0
    np.testing.assert_allclose(thick.eigenvalues, full.eigenvalues,
                               rtol=1e-12)
    np.testing.assert_allclose(thick.eigenvalues, w[:2], atol=1e-9)
    # the Krylov workspace stayed bounded at the configured cap: every
    # restart event fired at a basis size within it
    evs = [e for e in obs.events("solver_restart_thick")]
    assert len(evs) == thick.restarts
    assert all(e["basis_size"] <= e["cap"] for e in evs)
    assert all(e["cap"] == 16 for e in evs)
    # eigenvectors from the restarted basis are genuine eigenvectors
    v = thick.eigenvectors[0]
    hv = np.asarray(eng.matvec(v))
    r = np.linalg.norm(hv - thick.eigenvalues[0] * np.asarray(v))
    assert r < 1e-8, r


def test_thick_restart_streamed_engine(chain12, chain12_streamed):
    """The memory-bounded solve drives a streamed engine (the chain_36
    rung's solver loop) and lands the same E0."""
    op, h, _ = chain12
    eng = chain12_streamed
    w = np.linalg.eigvalsh(h)
    res = lanczos_block(eng.matvec, k=1, tol=1e-12, max_iters=400,
                        seed=4, max_basis_size=12)
    assert res.converged and res.restarts > 0
    assert abs(res.eigenvalues[0] - w[0]) < 1e-9


def test_lanczos_refusal_points_at_solver_table(chain12_streamed):
    with pytest.raises(ValueError, match="solve.kpm"):
        lanczos(chain12_streamed.matvec, n=8)
    with pytest.raises(NotImplementedError, match="solve.evolve"):
        chain12_streamed.bound_matvec()


@pytest.mark.slow
def test_thick_restart_chain_24_symm_acceptance():
    """The §29 acceptance rung: chain_24_symm E0 at rtol 1e-12 with the
    Krylov workspace bounded at the configured restart width, on a
    streamed engine."""
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine
    op = build_heisenberg(24, 12, 1, [([*range(1, 24), 0], 0),
                                      ([*reversed(range(24))], 0)])
    op.basis.build()
    eng = DistributedEngine(op, n_devices=1, mode="streamed")
    obs.reset()
    full = lanczos_block(eng.matvec, k=1, tol=1e-13, max_iters=260,
                         seed=3)
    thick = lanczos_block(eng.matvec, k=1, tol=1e-13, max_iters=600,
                          seed=3, max_basis_size=24)
    assert thick.restarts > 0
    evs = [e for e in obs.events("solver_restart_thick")]
    assert all(e["basis_size"] <= 24 for e in evs)
    np.testing.assert_allclose(thick.eigenvalues[0], full.eigenvalues[0],
                               rtol=1e-12)


# ---------------------------------------------------------------------------
# observables


def test_observables_vs_dense(chain12):
    import jax.numpy as jnp

    from distributed_matvec_tpu.models.observables import (
        bind_observables, expectations)
    op, h, eng = chain12
    n = op.basis.number_states
    psi_r = _rand_like((n,), np.float64, 3)
    psi_r /= np.linalg.norm(psi_r)
    psi_c = _rand_like((n,), np.complex128, 4)
    psi_c /= np.linalg.norm(psi_c)
    bo = bind_observables([op], eng)[0]
    assert bo.name
    want_r = float(psi_r @ (h @ psi_r))
    want_c = float(np.real(psi_c.conj() @ (h @ psi_c)))
    assert bo.expectation(jnp.asarray(psi_r)) == pytest.approx(
        want_r, abs=1e-10)
    # COMPLEX state against a real-sector O: the 2-column real block
    assert bo.expectation(jnp.asarray(psi_c)) == pytest.approx(
        want_c, abs=1e-10)
    vals = expectations([op], eng, jnp.asarray(psi_c))
    assert vals[0][1] == pytest.approx(want_c, abs=1e-10)


def test_observables_hashed_layout(chain12, chain12_streamed):
    from distributed_matvec_tpu.models.observables import bind_observables
    op, h, _ = chain12
    eng = chain12_streamed
    n = op.basis.number_states
    psi = _rand_like((n,), np.complex128, 6)
    psi /= np.linalg.norm(psi)
    want = float(np.real(psi.conj() @ (h @ psi)))
    bo = bind_observables([op], eng, mode="fused")[0]
    got = bo.expectation(eng.to_hashed(psi))
    assert got == pytest.approx(want, abs=1e-10)


def test_observable_complex_sector_native():
    import jax.numpy as jnp

    from distributed_matvec_tpu.models.observables import bind_observables
    op = build_heisenberg(8, 4, None, [([*range(1, 8), 0], 1)])
    op.basis.build()
    h = dense_effective_matrix(op)
    eng = LocalEngine(op)
    n = op.basis.number_states
    psi = _rand_like((n,), np.complex128, 2)
    psi /= np.linalg.norm(psi)
    want = float(np.real(psi.conj() @ (h @ psi)))
    bo = bind_observables([op], eng)[0]
    assert bo.expectation(jnp.asarray(psi)) == pytest.approx(
        want, abs=1e-10)


# ---------------------------------------------------------------------------
# serve integration


def test_jobspec_solver_kinds_validate():
    from distributed_matvec_tpu.serve import JobSpec
    base = dict(job_id="j", basis={"number_spins": 8, "hamming_weight": 4})
    s = JobSpec(**base, solver="kpm", n_moments=64)
    assert s.pricing()["solver"] == "kpm"
    assert s.pricing()["n_moments"] == 64
    with pytest.raises(ValueError, match="solver kind"):
        JobSpec(**base, solver="dmrg")
    with pytest.raises(ValueError, match="n_moments"):
        JobSpec(**base, solver="kpm", n_moments=1)
    with pytest.raises(ValueError, match="t_final"):
        JobSpec(**base, solver="evolve", t_final=0.0)
    # solver kind does NOT change the engine key (same warm engine)
    assert s.engine_key() == JobSpec(**base).engine_key()
    # round trip
    s2 = JobSpec.from_json(s.to_json())
    assert s2.solver == "kpm" and s2.n_moments == 64


def test_price_job_prices_dynamics():
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "capacity.py")
    spec = importlib.util.spec_from_file_location("dmt_capacity_t", path)
    cap = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cap)
    rates = {"gather_rows_per_s": 5e8, "flops_per_s": 5e9,
             "h2d_bytes_per_s": 3e9, "exchange_bytes_per_s": 3e9}
    base = {"n_states": 1 << 16, "num_terms": 16, "mode": "streamed",
            "n_devices": 1, "pair": False, "k": 1, "max_iters": 400}
    p_e = cap.price_job(dict(base), calibration=rates)
    p_k = cap.price_job(dict(base, solver="kpm", n_moments=256,
                             n_vectors=4), calibration=rates)
    p_v = cap.price_job(dict(base, solver="evolve", t_final=4.0,
                             krylov_dim=24), calibration=rates)
    assert p_e["priced"] and p_k["priced"] and p_v["priced"]
    # kpm: ceil(256/2)*4 + bounds columns; evolve: steps*m*2
    assert p_k["est_iters"] == 128 * 4 + cap.KPM_BOUNDS_COLUMNS
    assert p_v["est_iters"] == int(np.ceil(
        cap.EVOLVE_STEPS_PER_UNIT_TIME * 4.0)) * 24 * 2
    for p in (p_k, p_v):
        assert p["est_solve_s"] is not None and p["est_solve_s"] > 0
    # moment/step budgets actually move the price
    p_k2 = cap.price_job(dict(base, solver="kpm", n_moments=512,
                              n_vectors=4), calibration=rates)
    assert p_k2["est_solve_s"] > p_k["est_solve_s"]


def test_scheduler_runs_dynamics_jobs():
    """End-to-end: kpm + evolve + eigs jobs of ONE basis drain through
    the scheduler sharing ONE warm engine; dynamics jobs run one per
    batch, results carry their kind-specific fields."""
    from distributed_matvec_tpu.serve import (EnginePool, JobQueue,
                                              JobSpec, Scheduler)
    basis = {"number_spins": 10, "hamming_weight": 5}
    queue, pool = JobQueue(), EnginePool()
    sched = Scheduler(queue=queue, pool=pool, rates=None, block_width=4)
    specs = [
        JobSpec(job_id="eig0", basis=dict(basis), k=1, tol=1e-9,
                max_iters=200),
        JobSpec(job_id="kpm0", basis=dict(basis), solver="kpm",
                n_moments=48, n_vectors=2),
        JobSpec(job_id="ev0", basis=dict(basis), solver="evolve",
                t_final=0.5, krylov_dim=12, tol=1e-10),
    ]
    for s in specs:
        sched.submit(s)
    n_done = sched.drain(scan_spool=False)
    assert n_done == 3
    assert pool.builds == 1 and pool.hits == 2, (pool.builds, pool.hits)
    rk = queue.result("kpm0")
    assert rk["status"] == "done" and rk["solver"] == "kpm"
    assert len(rk["moments_head"]) == 8
    assert rk["moments_head"][0] == pytest.approx(1.0)
    rv = queue.result("ev0")
    assert rv["status"] == "done" and rv["solver"] == "evolve"
    assert rv["converged"] and rv["norm_drift"] < 1e-11
    re_ = queue.result("eig0")
    assert re_["status"] == "done" and re_["eigenvalues"]


def test_scheduler_packs_dynamics_singly():
    from distributed_matvec_tpu.serve import JobQueue, JobSpec, Scheduler
    basis = {"number_spins": 8, "hamming_weight": 4}
    queue = JobQueue()
    sched = Scheduler(queue=queue, rates=None, block_width=4)
    for i in range(3):
        queue.submit(JobSpec(job_id=f"k{i}", basis=dict(basis),
                             solver="kpm", n_moments=16,
                             submit_ts=float(i + 1)))
    batch = sched.next_batch()
    assert len(batch) == 1 and batch[0].job_id == "k0"


# ---------------------------------------------------------------------------
# the REAL 2-process leg


def test_multihost_dynamics_two_ranks(tmp_path):
    """2-process run (multihost worker harness, dynamics leg):
    rank-local streamed engines drive KPM + evolve on both ranks; the
    printed moment/energy agree across ranks to full precision and each
    rank built exactly ONE engine for both solvers."""
    import importlib.util
    import socket
    import subprocess

    rep_path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "tools", "obs_report.py")
    spec = importlib.util.spec_from_file_location("obs_report_dyn",
                                                  rep_path)
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    run = tmp_path / "dyn_run"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["DMT_MH_DYN"] = "1"
    env["DMT_OBS_DIR"] = str(run)
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    mu1, e0 = {}, {}
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]
        for line in out.splitlines():
            if line.startswith(f"[p{pid}] DYN_MU1 "):
                mu1[pid] = float(line.split()[-1])
            if line.startswith(f"[p{pid}] DYN_E "):
                e0[pid] = float(line.split()[-1])
    # identical rank-local problems: cross-rank agreement to the bit
    assert mu1[0] == mu1[1], mu1
    assert e0[0] == e0[1], e0
    events = rep.load_events(str(run))
    for r in (0, 1):
        inits = [e for e in events if e["rank"] == r
                 and e["kind"] == "engine_init"]
        assert len(inits) == 1, [e.get("engine") for e in inits]
