"""Operator tables + host matvec vs the independent dense projected matrix.

This is the heart of the correctness story: the production pipeline
(nonbranching masks → state_info canonicalization → χ·norm-ratio rescale,
mirroring BatchedOperator.chpl:82-213) must reproduce B†·H_full·B computed by
explicit Kronecker/projector algebra, to the reference tolerances
(atol 1e-14 / rtol 1e-12, TestMatrixVectorProduct.chpl:15-16).
"""

import numpy as np
import pytest

from distributed_matvec_tpu.models.basis import SpinBasis
from distributed_matvec_tpu.models.lattices import (
    chain_edges,
    heisenberg_from_edges,
    kagome_12_edges,
)
from distributed_matvec_tpu.models.expression import parse_expression
from distributed_matvec_tpu.models.operator import Operator

import dense_ref

ATOL, RTOL = 1e-14, 1e-12


def dense_expr_pairs(op):
    """Re-parse the operator's defining expressions for the dense path."""
    return op._dense_exprs  # attached by helpers below


def build_heisenberg(n, hw=None, inv=None, syms=(), edges=None):
    basis = SpinBasis(n, hw, inv, syms)
    edges = edges if edges is not None else chain_edges(n)
    op = heisenberg_from_edges(basis, edges)
    sites = [list(e) for e in edges]
    op._dense_exprs = [
        (parse_expression("σˣ₀ σˣ₁"), sites),
        (parse_expression("σʸ₀ σʸ₁"), sites),
        (parse_expression("σᶻ₀ σᶻ₁"), sites),
    ]
    return op


def dense_effective_matrix(op):
    basis = op.basis
    h_full = dense_ref.operator_matrix_full(basis.number_spins, op._dense_exprs)
    reps, norms = dense_ref.brute_force_representatives(
        basis.number_spins, basis.representatives, basis.group
    )
    np.testing.assert_array_equal(reps, basis.representatives)
    return dense_ref.projected_matrix(
        basis.number_spins, h_full, basis.representatives, basis.norms, basis.group
    )


CONFIGS = [
    # (n, hw, inv, syms) — mirroring the reference's config matrix shapes
    (4, 2, None, ()),
    (6, 3, None, ()),
    (8, 4, None, ()),
    (10, 5, -1, ()),  # heisenberg_chain_10.yaml sector
    (8, 4, 1, ()),
    (8, None, None, ()),
    (8, 4, None, [([1, 2, 3, 4, 5, 6, 7, 0], 0)]),
    (8, 4, 1, [([1, 2, 3, 4, 5, 6, 7, 0], 0), ([7, 6, 5, 4, 3, 2, 1, 0], 0)]),
    (10, 5, None, [([1, 2, 3, 4, 5, 6, 7, 8, 9, 0], 1)]),  # complex characters
    (12, 6, 1, [([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 0], 0),
                ([11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1, 0], 0)]),  # chain_24_symm shape
]


@pytest.mark.parametrize("n,hw,inv,syms", CONFIGS)
def test_matvec_host_matches_dense(n, hw, inv, syms, rng):
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    h_eff = dense_effective_matrix(op)
    # Hermiticity of the projected matrix (sanity of the dense path itself)
    np.testing.assert_allclose(h_eff, h_eff.conj().T, atol=1e-12)
    x = rng.random(op.basis.number_states) - 0.5
    y_ref = h_eff @ x
    y = op.matvec_host(x, batch_size=257)  # odd batch to exercise chunk edges
    if op.effective_is_real:
        assert np.abs(y_ref.imag).max() < 1e-12
        y_ref = y_ref.real
    else:
        x = x.astype(np.complex128)
        y = op.matvec_host(x, batch_size=257)
    np.testing.assert_allclose(y, y_ref, atol=ATOL * max(1, n), rtol=RTOL)


@pytest.mark.parametrize("n,hw,inv,syms", CONFIGS)
def test_to_sparse_matches_dense(n, hw, inv, syms):
    # covers projected bases and complex-character sectors too — the
    # off-diagonal source indexing relies on amps keeping [B, T] order
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    h_eff = dense_effective_matrix(op)
    ours = np.asarray(op.to_sparse().todense())
    np.testing.assert_allclose(ours, h_eff, atol=1e-12)


def test_issue_01_regression(rng):
    """data/issue_01.yaml: kagome-12 with a period-2 permutation, sector 1,
    and two couplings (1.0 and 0.8)."""
    perm = [2, 10, 0, 4, 3, 7, 11, 5, 9, 8, 1, 6]
    basis = SpinBasis(12, 6, None, [(perm, 1)])
    lattice_1 = [[0, 1], [1, 2], [0, 3], [3, 5], [5, 6], [6, 7], [4, 7], [2, 4],
                 [5, 8], [8, 0], [9, 2], [7, 9], [2, 10], [10, 0], [7, 11], [11, 5]]
    lattice_2 = [[1, 3], [6, 4], [6, 8], [1, 9], [10, 4], [11, 3], [11, 9], [10, 8]]
    from distributed_matvec_tpu.models.operator import Operator

    exprs = []
    dense_exprs = []
    for e in ["σˣ₀ σˣ₁", "σʸ₀ σʸ₁", "σᶻ₀ σᶻ₁"]:
        exprs.append((e, lattice_1))
        dense_exprs.append((parse_expression(e), lattice_1))
    for e in ["0.8 × σˣ₀ σˣ₁", "0.8 × σʸ₀ σʸ₁", "0.8 × σᶻ₀ σᶻ₁"]:
        exprs.append((e, lattice_2))
        dense_exprs.append((parse_expression(e), lattice_2))
    op = Operator.from_expressions(basis, exprs)
    op._dense_exprs = dense_exprs
    basis.build()
    assert op.is_hermitian
    h_eff = dense_effective_matrix(op)
    x = rng.random(basis.number_states) - 0.5
    y = op.matvec_host(x)
    y_ref = h_eff @ x
    if op.effective_is_real:
        y_ref = y_ref.real
    np.testing.assert_allclose(y, y_ref, atol=1e-13, rtol=RTOL)


def test_hermiticity_and_reality_flags():
    op = build_heisenberg(6, 3)
    assert op.is_hermitian and op.is_real
    # number_off_diag_terms counts flip-mask groups = number of bonds
    assert op.number_off_diag_terms == 6


def test_heisenberg_ground_energy_chain_8():
    """E₀ of the σ-Heisenberg 8-ring (hw sector), a published exact value:
    E₀/J = 4·Σ S·S eigen — cross-check against dense eigendecomposition."""
    op = build_heisenberg(8, 4)
    op.basis.build()
    import scipy.sparse.linalg as sla

    h = op.to_sparse()
    e0 = sla.eigsh(h, k=1, which="SA")[0][0]
    h_eff = dense_effective_matrix(op)
    e0_ref = np.linalg.eigvalsh(h_eff)[0]
    np.testing.assert_allclose(e0, e0_ref, atol=1e-10)


def test_operator_algebra(rng):
    """H = a*op1 + op2 - op3 front-end parity with the reference's
    expression algebra: matvec of the combination equals the combination of
    matvecs, and engines accept the result."""
    basis = SpinBasis(8)   # unconstrained: each piece is sector-valid alone
    sites = [[i, (i + 1) % 8] for i in range(8)]
    xx = Operator.from_expressions(basis, [("σˣ₀ σˣ₁", sites)], name="xx")
    yy = Operator.from_expressions(basis, [("σʸ₀ σʸ₁", sites)], name="yy")
    zz = Operator.from_expressions(basis, [("σᶻ₀ σᶻ₁", sites)], name="zz")
    basis.build()
    H = xx + yy + 0.5 * zz - 0.25 * zz
    x = rng.random(basis.number_states) - 0.5
    want = (xx.matvec_host(x) + yy.matvec_host(x)
            + 0.25 * zz.matvec_host(x))
    np.testing.assert_allclose(H.matvec_host(x), want, atol=1e-13)
    # scalar mul alone, negation, and same-basis enforcement
    np.testing.assert_allclose((2.0 * zz).matvec_host(x),
                               2 * zz.matvec_host(x), atol=1e-13)
    np.testing.assert_allclose((-zz).matvec_host(x), -zz.matvec_host(x),
                               atol=1e-13)
    other = SpinBasis(8)
    foreign = Operator.from_expressions(other, [("σᶻ₀ σᶻ₁", sites)])
    with pytest.raises(ValueError, match="different bases"):
        _ = zz + foreign
    # the combined operator runs through the jitted engine
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    eng = LocalEngine(H)
    np.testing.assert_allclose(np.asarray(eng.matvec(x)), want,
                               atol=1e-13, rtol=1e-12)


def test_operator_algebra_names():
    basis = SpinBasis(4)
    s = [[0, 1]]
    a = Operator.from_expressions(basis, [("σᶻ₀ σᶻ₁", s)], name="a")
    b = Operator.from_expressions(basis, [("σˣ₀ σˣ₁", s)], name="b")
    assert (a + b).name == "a + b"
    assert (a - b).name == "a - b"
    assert (2.0 * a).name == "2.0·a"
    assert (-a).name == "-a"


def test_state_info_coset_loop_paths_agree(monkeypatch, rng):
    """The unrolled (J ≤ _COSET_UNROLL_MAX) and dynamic-fori coset-scan paths
    of the device state_info must agree bit-for-bit — the dynamic path is
    what large 2-D groups (square_6x6: J=48) compile in reasonable time."""
    import jax
    import jax.numpy as jnp

    from distributed_matvec_tpu.ops import kernels as K

    op = build_heisenberg(
        12, 6, 1, [([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 0], 0),
                   ([11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1, 0], 0)])
    op.basis.build()
    t = K.device_tables(op)
    J = t.group.elem.shape[0]
    assert J > 1, "need a multi-coset group for this test"
    states = jnp.asarray(
        rng.integers(0, 1 << 12, 4096, dtype=np.uint64) | np.uint64(0))

    rep_u, char_u, norm_u = jax.jit(K.state_info)(t.group, states)
    monkeypatch.setattr(K, "_COSET_UNROLL_MAX", 0)   # force the fori path
    rep_d, char_d, norm_d = jax.jit(
        lambda g, s: K.state_info(g, s))(t.group, states)
    np.testing.assert_array_equal(np.asarray(rep_u), np.asarray(rep_d))
    np.testing.assert_array_equal(np.asarray(char_u), np.asarray(char_d))
    np.testing.assert_array_equal(np.asarray(norm_u), np.asarray(norm_d))
