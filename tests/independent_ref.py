"""Independent σ-Heisenberg reference — shares NOTHING with the package.

The golden harness above 12 sites previously checked engine-vs-matvec_host,
both of which consume ``models/expression.py``'s term tables; a bug in the
term compiler would cancel out.  This module builds H·x from the textbook
definition directly — pure NumPy bit operations, no expression parsing, no
term tables, no hashing — the same independence role the reference's
OpenMP-generated goldens play (SURVEY.md §4, input_for_matvec.py).

Works for ANY bond list (rings, squares, kagome, …): the edge list is part
of the problem *specification* (shared with the engine exactly as the
reference shares its YAML), while everything derived from it — matrix
elements, indices, signs — is computed here from the definition alone.

Convention matches the package's YAML models: σ-form Pauli matrices (4× the
spin-1/2 S-form), H = Σ_⟨ij⟩ σˣᵢσˣⱼ + σʸᵢσʸⱼ + σᶻᵢσᶻⱼ over the bonds:
  * σᶻᵢσᶻⱼ |s⟩ = ±|s⟩  (+ if bits i, j equal, − otherwise)
  * (σˣᵢσˣⱼ + σʸᵢσʸⱼ) |s⟩ = 2·|s with bits i, j swapped⟩ if they differ,
    else 0.
"""

from itertools import combinations

import numpy as np


def enumerate_fixed_hw(n: int, hw: int) -> np.ndarray:
    """All n-bit states with ``hw`` bits set, ascending (independent of the
    package's enumeration: itertools position sets, not bit tricks)."""
    states = np.fromiter(
        (sum(1 << p for p in pos) for pos in combinations(range(n), hw)),
        dtype=np.uint64)
    return np.sort(states)


def heisenberg_apply(states: np.ndarray, edges, x: np.ndarray) -> np.ndarray:
    """y = H·x on the fixed-hw sector spanned by sorted ``states``, for an
    arbitrary bond list ``edges`` (pairs may repeat — each occurrence is a
    physical coupling, e.g. doubled wrap bonds on a width-2 torus)."""
    y = np.zeros_like(x, dtype=np.float64)
    s = states
    for i, j in edges:
        bi = (s >> np.uint64(i)) & np.uint64(1)
        bj = (s >> np.uint64(j)) & np.uint64(1)
        differ = bi != bj
        # σᶻσᶻ: diagonal ±1 per bond
        y += np.where(differ, -1.0, 1.0) * x
        # σˣσˣ + σʸσʸ: amplitude 2 to the spin-swapped state
        flip = s[differ] ^ np.uint64((1 << i) | (1 << j))
        idx = np.searchsorted(s, flip)
        assert (s[idx] == flip).all(), "flipped state left the sector"
        np.add.at(y, idx, 2.0 * x[differ])
    return y


def heisenberg_ring_apply(states: np.ndarray, n: int,
                          x: np.ndarray) -> np.ndarray:
    """y = H·x for the n-site periodic ring (edge-list special case)."""
    return heisenberg_apply(states, [(i, (i + 1) % n) for i in range(n)], x)


def ground_energy(n: int, hw: int, edges, tol: float = 1e-12, k: int = 1):
    """Lowest eigenvalue(s) of the full fixed-hw sector via ARPACK over the
    independent apply."""
    from scipy.sparse.linalg import LinearOperator, eigsh

    states = enumerate_fixed_hw(n, hw)
    N = states.size
    op = LinearOperator(
        (N, N), matvec=lambda v: heisenberg_apply(states, edges, v),
        dtype=np.float64)
    w = eigsh(op, k=k, which="SA", tol=tol, return_eigenvectors=False)
    w = np.sort(w)
    return (float(w[0]) if k == 1 else w), states


def ring_ground_energy(n: int, hw: int, tol: float = 1e-12):
    """Ring special case of :func:`ground_energy` (the ground state of the
    bipartite ring lives in the fully symmetric momentum/parity/inversion
    sector, so this also pins the *_symm configs' E0)."""
    return ground_energy(n, hw, [(i, (i + 1) % n) for i in range(n)],
                         tol=tol)
