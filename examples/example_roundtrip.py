#!/usr/bin/env python
"""Round-trip layout-shuffle property check (Example02 analog).

The reference's ``example/Example02.chpl:20-48`` fabricates a rank-2 batch of
vectors, pushes it hashed→block→hashed, and asserts identity.  Here the same
property runs through :class:`~distributed_matvec_tpu.parallel.shuffle.HashedLayout`
on a fabricated basis (every u64 in a range) with a [N, k] batch.

Usage: python examples/example_roundtrip.py [--n 10000] [--shards 8] [--batch 3]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10_000)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--batch", type=int, default=3)
    args = ap.parse_args()

    from distributed_matvec_tpu.parallel.shuffle import HashedLayout

    rng = np.random.default_rng(0)
    states = np.sort(rng.choice(1 << 40, size=args.n, replace=False)
                     .astype(np.uint64))
    x = rng.standard_normal((args.n, args.batch))

    layout = HashedLayout(states, args.shards)
    xh = layout.to_hashed(x)                       # block → hashed [D, M, k]
    back = layout.from_hashed(xh)                  # hashed → block [N, k]
    assert np.array_equal(back, x), "round trip failed"
    print(f"round trip ok: N={args.n}, D={args.shards}, batch={args.batch}, "
          f"shard size {layout.shard_size} "
          f"(imbalance {layout.counts.max() / layout.counts.mean():.3f})")


if __name__ == "__main__":
    main()
