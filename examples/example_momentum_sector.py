#!/usr/bin/env python
"""Momentum-sector diagonalization — complex characters, TPU-safe pair form.

Resolves the lowest levels of a Heisenberg ring in one translation-momentum
sector k (characters e^{-2πik·s/L}).  For k ∉ {0, L/2} the sector's effective
Hamiltonian is complex-Hermitian; on the TPU backend the engines run it in
(re, im)-f64 *pair* form automatically (``complex_pair="auto"`` — no
complex128 ever reaches the device), and the J-aware Lanczos resolves each
eigenvalue once.  On CPU the same script runs in native complex128.

The full spectrum of the ring is the union over k of the sector spectra —
compare: ``for k in 0..L-1: python examples/example_momentum_sector.py -k K``.

Usage:
    python examples/example_momentum_sector.py --num-spins 12 -k 2 --evals 3
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

from distributed_matvec_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-spins", type=int, default=12)
    ap.add_argument("-k", "--sector", type=int, default=1,
                    help="translation-momentum sector (0..L-1)")
    ap.add_argument("--evals", type=int, default=3)
    ap.add_argument("--tol", type=float, default=1e-10)
    args = ap.parse_args()

    import jax

    from distributed_matvec_tpu.models.basis import SpinBasis
    from distributed_matvec_tpu.models.lattices import (chain_edges,
                                                        heisenberg_from_edges)
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    from distributed_matvec_tpu.solve import lanczos

    n = args.num_spins
    basis = SpinBasis(n, n // 2,
                      symmetries=[([*range(1, n), 0], args.sector)])
    op = heisenberg_from_edges(basis, chain_edges(n))
    t0 = time.time()
    basis.build()
    print(f"sector k={args.sector}: N={basis.number_states} states "
          f"({time.time() - t0:.2f}s)")

    eng = LocalEngine(op)
    print(f"backend={jax.default_backend()}  "
          f"effective_is_real={op.effective_is_real}  pair={eng.pair}")

    t0 = time.time()
    res = lanczos(eng.matvec, basis.number_states, k=args.evals,
                  tol=args.tol, compute_eigenvectors=True)
    print(f"lanczos: {res.num_iters} iters in {time.time() - t0:.2f}s, "
          f"converged={res.converged}")
    for i, (w, r) in enumerate(zip(res.eigenvalues, res.residual_norms)):
        print(f"  E[{i}] = {w:.12f}   residual {r:.2e}")

    # cross-check the ground state via the independent host path
    v = np.asarray(res.eigenvectors[0])
    if eng.pair:
        from distributed_matvec_tpu.ops.kernels import complex_from_pair
        v = complex_from_pair(v)
    hv = op.matvec_host(v)
    print(f"  |H·v − E0·v| (host path) = "
          f"{np.linalg.norm(hv - res.eigenvalues[0] * v):.2e}")


if __name__ == "__main__":
    main()
