#!/usr/bin/env python
"""Parameterized matvec driver with timing (Example05 analog).

The reference's ``example/Example05.chpl`` builds a configurable system
(``--kSystem``, ``--kNumSpins``), enumerates the basis, runs the distributed
matvec, and prints phase timings.  Same here, on the JAX default backend.

Usage:
    python examples/example_matvec.py --system chain --num-spins 20
    python examples/example_matvec.py --system chain --num-spins 24 --symm \
        --devices 8 --repeats 5
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def build(system, n, symm):
    from distributed_matvec_tpu.models.basis import SpinBasis
    from distributed_matvec_tpu.models.lattices import (
        chain_edges, heisenberg_from_edges, kagome_12_edges, kagome_16_edges)

    if system == "chain":
        edges = chain_edges(n)
    elif system == "kagome":
        edges = {12: kagome_12_edges, 16: kagome_16_edges}[n]()
    else:
        raise SystemExit(f"unknown system {system!r}")
    syms, inv = (), None
    if symm:
        syms = [([*range(1, n), 0], 0), ([*reversed(range(n))], 0)]
        inv = 1
    basis = SpinBasis(n, n // 2, inv, syms)
    return heisenberg_from_edges(basis, edges)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--system", default="chain", choices=("chain", "kagome"))
    ap.add_argument("--num-spins", type=int, default=20)
    ap.add_argument("--symm", action="store_true",
                    help="translation+parity+inversion sector")
    ap.add_argument("--devices", type=int, default=0,
                    help="shard over an n-device mesh (0 = single device)")
    ap.add_argument("--repeats", type=int, default=10)
    args = ap.parse_args()

    import jax

    op = build(args.system, args.num_spins, args.symm)
    t0 = time.perf_counter()
    op.basis.build()
    t_build = time.perf_counter() - t0
    n = op.basis.number_states
    print(f"basis: N={n} states in {t_build:.3f}s")

    rng = np.random.default_rng(42)
    x = rng.standard_normal(n)
    x /= np.linalg.norm(x)

    t0 = time.perf_counter()
    if args.devices > 1:
        from distributed_matvec_tpu.parallel.distributed import (
            DistributedEngine)
        eng = DistributedEngine(op, n_devices=args.devices)
        xd = eng.to_hashed(x)
    else:
        from distributed_matvec_tpu.parallel.engine import LocalEngine
        eng = LocalEngine(op)
        xd = jax.numpy.asarray(x)
    print(f"engine init (incl. structure build): "
          f"{time.perf_counter() - t0:.3f}s")

    y = jax.block_until_ready(eng.matvec(xd))      # compile + check
    t0 = time.perf_counter()
    for _ in range(args.repeats):
        y = eng.matvec(xd)
    jax.block_until_ready(y)
    dt = (time.perf_counter() - t0) / args.repeats
    print(f"matvec: {dt * 1e3:.3f} ms/apply "
          f"({args.repeats} repeats, backend={jax.default_backend()})")


if __name__ == "__main__":
    main()
