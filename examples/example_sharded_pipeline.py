#!/usr/bin/env python
"""End-to-end SHARD-NATIVE pipeline: no global array at any stage.

The ≥10⁹-state workflow (the reference's distributed-memory regime,
README.md:69-116) at demo size:

  1. enumerate the sector straight into per-shard datasets — optionally
     with several OS processes, each streaming its cyclic chunk set into
     its own part file (StatesEnumeration.chpl:321-334 analog),
  2. census-validate the union (pure combinatorics, shares nothing with
     the enumeration kernels),
  3. build a plan-mode DistributedEngine from the shard file (peer shards
     are streamed from disk one at a time; per-shard structure cache),
  4. solve in hashed space with mid-solve checkpointing,
  5. save eigenvectors per shard (vector_shards/eigenvector_i).

Usage:
    python examples/example_sharded_pipeline.py --num-spins 16 --ranks 2
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def _enum_rank(args):
    n, hw, n_shards, path, rank, n_ranks = args
    from distributed_matvec_tpu.enumeration.sharded import enumerate_to_shards
    from distributed_matvec_tpu.models.basis import SpinBasis

    b = SpinBasis(number_spins=n, hamming_weight=hw)
    man = enumerate_to_shards(n, hw, b.group, n_shards, path,
                              rank=rank, n_ranks=n_ranks)
    return man["total"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-spins", type=int, default=16)
    ap.add_argument("--ranks", type=int, default=2,
                    help="enumerating OS processes")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--mode", default="compact",
                    choices=("ell", "compact", "fused"))
    ap.add_argument("--k", type=int, default=2, help="eigenpairs")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    # a plain CPU host exposes one device; virtualize the mesh before any
    # backend init (harmless when real accelerators provide the devices)
    if "xla_force_host_platform_device_count" \
            not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    n, hw = args.num_spins, args.num_spins // 2
    wd = args.workdir or tempfile.mkdtemp(prefix="dmt_sharded_")
    shards = os.path.join(wd, "shards.h5")
    print(f"workdir: {wd}")

    from distributed_matvec_tpu.enumeration.sharded import finalize_shard_parts
    from distributed_matvec_tpu.io.sharded_io import (hashed_vector_counts,
                                                      save_hashed_vectors)
    from distributed_matvec_tpu.models.basis import SpinBasis
    from distributed_matvec_tpu.models.lattices import (chain_edges,
                                                        heisenberg_from_edges)
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine
    from distributed_matvec_tpu.solve import lanczos

    # 1+2: multi-process enumeration + census-validated finalize
    t0 = time.time()
    basis_spec = SpinBasis(number_spins=n, hamming_weight=hw)
    if args.ranks > 1:
        import multiprocessing as mp
        from concurrent.futures import ProcessPoolExecutor

        ctx = mp.get_context("spawn")
        with ProcessPoolExecutor(max_workers=args.ranks,
                                 mp_context=ctx) as ex:
            totals = list(ex.map(_enum_rank, [
                (n, hw, args.devices, shards, r, args.ranks)
                for r in range(args.ranks)]))
        man = finalize_shard_parts(n, hw, basis_spec.group, args.devices,
                                   shards, args.ranks)
        print(f"enumerated {man['total']} representatives "
              f"({args.ranks} ranks, per-rank {totals}) "
              f"in {time.time() - t0:.1f} s — census OK")
    else:
        _enum_rank((n, hw, args.devices, shards, 0, 1))
        print(f"enumerated in {time.time() - t0:.1f} s")

    # 3: plan-mode engine straight from the shard file (+ per-shard cache);
    # the same (unbuilt) basis spec the census used carries the operator
    op = heisenberg_from_edges(basis_spec, chain_edges(n))
    t0 = time.time()
    eng = DistributedEngine.from_shards(
        op, shards, n_devices=args.devices, mode=args.mode,
        structure_cache=os.path.join(wd, "plan"))
    assert not op.basis.is_built          # the global basis never exists
    print(f"{args.mode} engine from shards in {time.time() - t0:.1f} s "
          f"(N={eng.n_states}, restored={eng.structure_restored})")

    # 4: hashed-space solve with mid-solve checkpointing
    t0 = time.time()
    res = lanczos(eng.matvec, v0=eng.random_hashed(seed=42), k=args.k,
                  tol=1e-10, compute_eigenvectors=True,
                  checkpoint_path=os.path.join(wd, "solver.h5"))
    print(f"lanczos: {res.num_iters} iters in {time.time() - t0:.1f} s, "
          f"converged={res.converged}")
    for i, (w, r) in enumerate(zip(res.eigenvalues, res.residual_norms)):
        print(f"  E[{i}] = {w:.12f}   residual {r:.2e}")

    # 5: per-shard eigenvector output
    out = os.path.join(wd, "eigen.h5")
    save_hashed_vectors(out, {f"eigenvector_{i}": v
                              for i, v in enumerate(res.eigenvectors)},
                        eng.counts)
    print(f"eigenvectors saved per shard to {out} "
          f"(counts {list(map(int, hashed_vector_counts(out)))})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
