#!/usr/bin/env python
"""Diagonalize: YAML model in → lowest-k eigenpairs + residuals out (HDF5).

The driver app — reference parity with ``bin/Diagonalize``
(``/root/reference/src/Diagonalize.chpl:258-332``):

  1. load the YAML config (basis + hamiltonian [+ observables]),
  2. build or *restore* the representative set from the output file
     (checkpoint semantics of ``makeBasisStates``, Diagonalize.chpl:227-246),
  3. run the eigensolver (Lanczos, or LOBPCG with --block) over the jitted
     engine — single device or an n-device mesh (--devices),
  4. save eigenvalues/eigenvectors/residuals into the output HDF5
     (Diagonalize.chpl:248-256) and print a summary (+ observable expectation
     values when requested).

Flags mirror the reference's config consts (Diagonalize.chpl:164-172).

Usage:
    python apps/diagonalize.py model.yaml -o out.h5 -k 2 --tol 1e-10
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

from distributed_matvec_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()


def main(argv=None):
    # root run span: every event of the run (engine builds, solver
    # iterations, applies, the save epilogue) becomes a descendant of one
    # `diagonalize` span, and the trace-id stamp resolves lazily AFTER
    # _main() points obs at the run directory — the span event itself is
    # written by the line-buffered sink + atexit flush backstop
    from distributed_matvec_tpu.obs import trace as _trace

    with _trace.span("diagonalize", kind="run"):
        return _main(argv)


def _main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="exit codes: 0 solved, 2 bad config/arguments, "
               "75 preempted (SIGTERM/SIGINT latched; a checkpoint was "
               "written at the last safe point — relaunch the SAME argv "
               "to resume; apps/solve_service.py uses the same code when "
               "draining), 76 stalled (a wedged peer rank tripped the "
               "heartbeat watchdog).  A supervisor should retry 75/76 "
               "and treat other nonzero codes as permanent.  "
               "Solver kinds served over the same engines: eigs "
               "(lowest-k eigenpairs — this app, and the JobSpec "
               "default for --submit), kpm (Chebyshev/KPM spectral "
               "densities) and evolve (Krylov exp(-iHt) time "
               "evolution) — the dynamics kinds run via "
               "apps/dynamics.py (same 75/76 contract) or a JobSpec "
               "with solver=kpm|evolve through the solve service "
               "(DESIGN.md §29).")
    ap.add_argument("input", help="YAML config (data/*.yaml schema)")
    ap.add_argument("-o", "--output", default=None,
                    help="output HDF5 (default: <input>.h5); also the "
                         "representative checkpoint (kOutput)")
    ap.add_argument("-k", "--num-evals", type=int, default=1,
                    help="number of eigenpairs (numEvals)")
    ap.add_argument("--tol", type=float, default=1e-10,
                    help="residual tolerance (kEps)")
    ap.add_argument("--max-iters", type=int, default=1000,
                    help="total Lanczos iteration cap")
    ap.add_argument("--max-basis-size", type=int, default=None,
                    help="Krylov basis bound before a thick restart "
                         "(kMaxBasisSize)")
    ap.add_argument("--min-restart-size", type=int, default=None,
                    help="Ritz vectors kept at a restart (kMinRestartSize)")
    ap.add_argument("--devices", type=int, default=0,
                    help="shard over an n-device mesh (0 = single device)")
    ap.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                    help="multi-host: jax.distributed coordinator address "
                         "(the GASNet-substrate analog; omit for "
                         "single-host or cluster auto-detection)")
    ap.add_argument("--num-processes", type=int, default=None,
                    help="multi-host: total process count")
    ap.add_argument("--process-id", type=int, default=None,
                    help="multi-host: this process's rank")
    ap.add_argument("--shards", default=None, metavar="SHARDS_H5",
                    help="construct the engine from a sharded-enumeration "
                         "file (tools/sharded_enum_scale.py) — the global "
                         "representative array is never built; the solve "
                         "stays in hashed space and eigenvectors are saved "
                         "per shard (vector_shards/eigenvector_<i>)")
    ap.add_argument("--mode", choices=("ell", "compact", "streamed",
                                       "fused", "hybrid"),
                    default=None,
                    help="engine mode: precomputed structure (ell, the "
                         "default), 4 B/entry for isotropic real sectors "
                         "(compact), the structure resolved once into a "
                         "host-RAM plan streamed per apply (streamed — "
                         "fused-level device memory, no per-apply orbit "
                         "scan; solved via the eager block-Lanczos), "
                         "recompute-on-the-fly (fused — the default with "
                         "--shards; plan builds also work shard-native, "
                         "streaming peer shards from the file, and are "
                         "worth their one-time cost for long solves), or "
                         "the per-term recompute-vs-stream split priced "
                         "by the calibrated cost model (hybrid — the "
                         "DMT_HYBRID knob picks the split policy; solved "
                         "via the eager block-Lanczos like streamed)")
    ap.add_argument("--block", action="store_true",
                    help="use LOBPCG (blocked) instead of Lanczos")
    ap.add_argument("--solver-checkpoint", default=None, metavar="CKPT_H5",
                    help="mid-solve Lanczos/LOBPCG checkpoint/resume file "
                         "(beyond the reference: PRIMME state is never "
                         "saved there); a rerun with the same config "
                         "resumes where it stopped — including after a "
                         "preemption exit (code 75)")
    ap.add_argument("--checkpoint-every", type=int, default=4,
                    help="solver-checkpoint cadence in convergence-check "
                         "blocks (each block is check_every=16 iterations; "
                         "the write costs a basis fetch, so raise this at "
                         "large N)")
    ap.add_argument("--no-eigenvectors", action="store_true",
                    help="skip eigenvector computation/saving")
    ap.add_argument("--observables", action="store_true",
                    help="evaluate ⟨ψ|O|ψ⟩ for YAML observables")
    ap.add_argument("--timings", action="store_true",
                    help="print phase timings (kDisplayTimings)")
    ap.add_argument("--obs-dir", default=None, metavar="DIR",
                    help="telemetry run directory (sets obs_dir / "
                         "DMT_OBS_DIR): engine-init splits, solver "
                         "convergence traces, rank-tagged apply events, and "
                         "phase timings stream to DIR/rank_<r>/events.jsonl "
                         "for tools/obs_report.py (merge / report --ranks "
                         "for multi-rank runs)")
    ap.add_argument("--job-id", default=None, metavar="ID",
                    help="job-namespacing id stamped into every telemetry "
                         "event (DMT_JOB_ID; default: the run's trace id) "
                         "— lets a scheduler multiplexing many concurrent "
                         "solves filter one job's events/spans out of a "
                         "shared stream (obs_report watch/trace read it)")
    ap.add_argument("--submit", action="store_true",
                    help="do not solve inline: enqueue this run as a job "
                         "spec in --serve-dir's spool for a running solve "
                         "service (apps/solve_service.py) and exit 0; the "
                         "service batches same-basis submissions through "
                         "one warm engine and writes the result to "
                         "<serve-dir>/done/<job_id>.json")
    ap.add_argument("--serve-dir", default=None, metavar="DIR",
                    help="solve-service spool directory for --submit "
                         "(created if missing)")
    ap.add_argument("--health", choices=("on", "strict", "off"),
                    default=None,
                    help="numerical-health watchdog (DMT_HEALTH): on = "
                         "log-and-continue (default), strict = critical "
                         "conditions (NaN/Inf outputs, exchange overflow, "
                         "Lanczos breakdown) raise HealthError, off = no "
                         "probes")
    args = ap.parse_args(argv)
    if args.mode is None:
        args.mode = "fused" if args.shards else "ell"

    if args.submit:
        # enqueue-and-exit: no engine, no solve, no JAX backend touch —
        # the job spec carries everything the service needs to rebuild
        # the model (the yaml path) and shape the engine
        if not args.serve_dir:
            print("--submit needs --serve-dir DIR (the service's spool)",
                  file=sys.stderr)
            return 2
        if args.shards or args.block:
            print("--submit covers single-operator Lanczos jobs; "
                  "--shards/--block runs stay inline", file=sys.stderr)
            return 2
        import uuid

        from distributed_matvec_tpu.serve import JobSpec, submit_to_spool

        job_id = args.job_id or f"cli-{uuid.uuid4().hex[:10]}"
        spec = JobSpec(job_id=job_id, yaml=os.path.abspath(args.input),
                       k=args.num_evals, tol=args.tol,
                       max_iters=args.max_iters, mode=args.mode,
                       n_devices=args.devices)
        path = submit_to_spool(args.serve_dir, spec)
        print(f"submitted job {job_id} -> {path}")
        print(f"result will land at "
              f"{os.path.join(args.serve_dir, 'done', job_id + '.json')}")
        return 0

    from distributed_matvec_tpu import obs
    from distributed_matvec_tpu.io import (
        make_or_restore_representatives, save_eigen)
    from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml
    from distributed_matvec_tpu.solve import lanczos, lobpcg
    from distributed_matvec_tpu.utils.config import update_config
    from distributed_matvec_tpu.utils.timers import TreeTimer

    if args.obs_dir:
        update_config(obs_dir=args.obs_dir)
    if args.job_id:
        # env AND config, same both-or-neither contract as --health: an
        # inherited DMT_JOB_ID must not outrank the id requested on the
        # command line, and child processes must inherit it
        os.environ["DMT_JOB_ID"] = args.job_id
        update_config(job_id=args.job_id)
    if args.health:
        # the env var outranks the config field (per-subprocess override
        # contract), so the CLI must set BOTH or an inherited DMT_HEALTH
        # would silently drop the mode requested on the command line
        os.environ["DMT_HEALTH"] = args.health
        update_config(health=args.health)

    if args.coordinator or args.num_processes:
        from distributed_matvec_tpu.parallel.mesh import init_distributed
        init_distributed(coordinator_address=args.coordinator,
                         num_processes=args.num_processes,
                         process_id=args.process_id)
    if args.timings:
        update_config(display_timings=True)
    # preemption latch BEFORE any long-running phase: a SIGTERM during the
    # basis/engine build still latches, and the solve exits at its first
    # safe point with a checkpoint + EXIT_PREEMPTED (resume = same argv)
    import signal as _signal

    from distributed_matvec_tpu.utils import preempt as _preempt
    from distributed_matvec_tpu.utils.preempt import (EXIT_PREEMPTED,
                                                      Preempted)
    # a batch driver opts Ctrl-C into the latch too (library solves
    # install SIGTERM only, keeping interactive KeyboardInterrupt alive)
    _preempt.ensure_installed(signals=(_signal.SIGTERM, _signal.SIGINT))
    import jax
    # multi-controller: every rank computes, rank 0 owns the output file
    # (the reference's locale-0 I/O role, MyHDF5.chpl:215-252)
    rank0 = jax.process_index() == 0
    out = args.output or os.path.splitext(args.input)[0] + ".h5"
    # cross-rank heartbeat watchdog (DMT_HEARTBEAT_S > 0): a hung peer
    # becomes a stall_report + EXIT_STALLED instead of an infinite
    # all_to_all wait
    watchdog = None
    from distributed_matvec_tpu.utils.config import get_config
    _cfg = get_config()
    if _cfg.heartbeat_s > 0 and jax.process_count() > 1:
        from distributed_matvec_tpu.parallel.heartbeat import (
            HeartbeatWatchdog)
        hb_dir = args.obs_dir or os.path.dirname(os.path.abspath(out))
        watchdog = HeartbeatWatchdog(
            hb_dir, interval_s=_cfg.heartbeat_s,
            timeout_s=_cfg.heartbeat_timeout_s).start()
    timer = TreeTimer("diagonalize")
    obs.emit("run_start", app="diagonalize", input=args.input, output=out,
             k=args.num_evals, devices=args.devices,
             mode=args.mode, block=bool(args.block))

    with timer.scope("load_config"):
        cfg = load_config_from_yaml(args.input, hamiltonian=True,
                                    observables=args.observables)
    if cfg.hamiltonian is None:
        print("config has no hamiltonian section", file=sys.stderr)
        return 2

    if args.shards:
        with timer.scope("engine"):
            from distributed_matvec_tpu.parallel.distributed import (
                DistributedEngine)
            eng = DistributedEngine.from_shards(
                cfg.hamiltonian, args.shards,
                n_devices=args.devices or None, mode=args.mode)
            v0 = eng.random_hashed(seed=42)
        n = eng.n_states
        print(f"basis: N={n} states (shard-native from {args.shards})")
    else:
        with timer.scope("basis"):
            # every rank restores from the same checkpoint (agreement even
            # against a stale file); only rank 0 writes it
            restored = make_or_restore_representatives(cfg.basis, out,
                                                       save=rank0)
        n = cfg.basis.number_states
        print(f"basis: N={n} states "
              f"({'restored from' if restored else 'checkpointed to'} {out})")

    if args.mode in ("streamed", "hybrid"):
        # fail BEFORE the engine pays the plan-resolution cost: pair-form
        # sectors (complex characters on a TPU mesh) have no in-tree
        # streamed solver — lanczos() cannot trace a streamed engine and
        # lanczos_block() has no J-aware reorthogonalization
        from distributed_matvec_tpu.parallel.engine import use_pair_complex
        if (not cfg.hamiltonian.effective_is_real) and use_pair_complex():
            print(f"--mode {args.mode} does not support pair-form complex "
                  "sectors (no streamed-compatible solver handles the "
                  "J-aware recurrence); use --mode ell/fused, or run the "
                  "sector native-c128 on CPU", file=sys.stderr)
            return 2

    with timer.scope("engine"):
        if args.shards:
            pass                              # engine built above
        elif (args.devices and args.devices > 1) \
                or args.mode in ("streamed", "hybrid"):
            from distributed_matvec_tpu.parallel.distributed import (
                DistributedEngine)
            # streamed/hybrid live on DistributedEngine; without
            # --devices they run the documented single-device form
            eng = DistributedEngine(cfg.hamiltonian,
                                    n_devices=args.devices or 1,
                                    mode=args.mode)
            v0 = eng.random_hashed(seed=42)
        else:
            from distributed_matvec_tpu.parallel.engine import LocalEngine
            eng = LocalEngine(cfg.hamiltonian, mode=args.mode)
            v0 = None

    from distributed_matvec_tpu.utils.profiling import maybe_profile

    resumed_from = 0
    try:
        with timer.scope("solve"), maybe_profile():
            t0 = time.perf_counter()
            if args.block:
                if jax.process_count() > 1 \
                        and not hasattr(eng, "from_hashed"):
                    print("--block (LOBPCG) in a multi-process run needs a "
                          "distributed engine (--devices or --shards)",
                          file=sys.stderr)
                    return 2
                evals, evecs_cols, iters = lobpcg(
                    eng.matvec, n, k=args.num_evals, tol=args.tol,
                    max_iters=args.max_iters,
                    checkpoint_path=args.solver_checkpoint,
                    # the flag counts Lanczos convergence-check blocks of
                    # check_every=16 iterations; LOBPCG segments count
                    # iterations directly, so scale for a comparable cadence
                    checkpoint_every=max(args.checkpoint_every, 1) * 16)
                # lobpcg returns block-order columns for both engines;
                # route the residual matvec through the block-facing entry
                # point
                mv_block = getattr(eng, "matvec_global", None) \
                    or (lambda v: np.asarray(eng.matvec(v)))
                evecs = [evecs_cols[:, i]
                         for i in range(evecs_cols.shape[1])]
                residuals = np.array([
                    float(np.linalg.norm(mv_block(v) - w * np.asarray(v)))
                    for w, v in zip(evals, evecs)])
                niter = iters
                # lobpcg's 3-tuple API carries no resume count — surface
                # the solver_resume event so a relaunched run prints the
                # same confirmation line Lanczos does
                resumed = [e for e in obs.events("solver_resume")
                           if e.get("solver") == "lobpcg"]
                if resumed:
                    resumed_from = int(resumed[-1]["iters"])
            elif args.mode in ("streamed", "hybrid"):
                # a streamed/hybrid engine cannot be traced into the
                # single-program Lanczos block runner — drive it with the
                # eager block solver (each k-column block streams the plan
                # once)
                from distributed_matvec_tpu.solve import lanczos_block
                if args.solver_checkpoint:
                    print("warning: --solver-checkpoint applies to the "
                          "single-vector Lanczos and LOBPCG; "
                          "streamed-mode block solves exit cleanly on "
                          "preemption but are not checkpointed",
                          file=sys.stderr)
                res = lanczos_block(eng.matvec, k=args.num_evals,
                                    tol=args.tol, max_iters=args.max_iters,
                                    seed=42,
                                    compute_eigenvectors=not
                                    args.no_eigenvectors)
                evals, residuals, niter = (res.eigenvalues,
                                           res.residual_norms,
                                           res.num_iters)
                evecs = res.eigenvectors
                if not res.converged:
                    print("warning: solver did not converge",
                          file=sys.stderr)
            else:
                res = lanczos(eng.matvec, n=None if v0 is not None else n,
                              v0=v0, k=args.num_evals, tol=args.tol,
                              max_iters=args.max_iters,
                              max_basis_size=args.max_basis_size,
                              min_restart_size=args.min_restart_size,
                              checkpoint_path=args.solver_checkpoint,
                              checkpoint_every=args.checkpoint_every,
                              compute_eigenvectors=not args.no_eigenvectors)
                evals, residuals, niter = (res.eigenvalues,
                                           res.residual_norms,
                                           res.num_iters)
                evecs = res.eigenvectors
                resumed_from = res.resumed_from
                if not res.converged:
                    print("warning: solver did not converge",
                          file=sys.stderr)
            dt = time.perf_counter() - t0
    except Preempted as e:
        # checkpoint-and-exit: the solver already wrote a generation-agreed
        # checkpoint (when configured) and flushed its events; close the
        # run's telemetry and hand the supervisor the distinct exit code —
        # a relaunch with the SAME argv resumes from the checkpoint
        print(f"preempted: {e}", file=sys.stderr)
        obs.emit("run_preempted", app="diagonalize", solver=e.solver,
                 iters=int(e.iters), checkpoint=e.checkpoint_path or "",
                 exit_code=EXIT_PREEMPTED)
        timer.emit(app="diagonalize")
        obs.emit("metrics_snapshot", metrics=obs.snapshot())
        obs.flush()
        if watchdog is not None:
            watchdog.stop()
        return EXIT_PREEMPTED
    if resumed_from:
        print(f"solver: resumed from {resumed_from} checkpointed "
              "iterations")
    print(f"solver: {niter} iterations in {dt:.2f}s "
          f"({niter / max(dt, 1e-9):.2f} iters/s)")
    obs.emit("diagonalize_result",
             eigenvalues=[float(w) for w in np.atleast_1d(evals)],
             residuals=[float(r) for r in np.atleast_1d(residuals)],
             iters=int(niter), solve_s=round(dt, 3))

    evec_rows = None
    evecs_hashed = None
    is_pair = bool(getattr(eng, "pair", False))
    hashed_ndim = 3 if is_pair else 2       # [D, M(, 2)] hashed layout
    if evecs is not None and not args.no_eigenvectors:
        if args.shards and all(np.ndim(v) == hashed_ndim
                               for v in evecs[: args.num_evals]):
            # shard-native solve: eigenvectors stay hashed and are saved
            # one shard at a time with pads stripped (the per-locale block
            # writes of MyHDF5.chpl:272-333) — no global [N] array is ever
            # materialized, so --shards no longer needs --no-eigenvectors
            evecs_hashed = evecs[: args.num_evals]
        else:
            rows = []
            for v in evecs[: args.num_evals]:
                # hashed → block order for I/O BEFORE any host fetch: in a
                # multi-controller run the hashed array spans other
                # processes' devices and from_hashed allgathers it
                if hasattr(eng, "from_hashed") and np.ndim(v) == hashed_ndim:
                    v = eng.from_hashed(v)
                v = np.asarray(v)
                if is_pair and not np.iscomplexobj(v):
                    # (re, im) pair → complex for I/O (LOBPCG already
                    # returns complex columns)
                    from distributed_matvec_tpu.ops.kernels import (
                        complex_from_pair)
                    v = complex_from_pair(v)
                rows.append(v)
            evec_rows = np.stack(rows)

    with timer.scope("save"):
        if rank0:
            save_eigen(out, np.asarray(evals), evec_rows,
                       np.asarray(residuals))
        if evecs_hashed is not None:
            # every rank writes its addressable shards (the save targets
            # out.r<rank> in multi-process runs); pair-mode vectors keep
            # the (re, im) trailing axis on disk; one file pass for all k
            from distributed_matvec_tpu.io.sharded_io import (
                save_hashed_vectors)
            save_hashed_vectors(
                out, {f"eigenvector_{i}": v
                      for i, v in enumerate(evecs_hashed)}, eng.counts)

    for i, (w, r) in enumerate(zip(np.atleast_1d(evals),
                                   np.atleast_1d(residuals))):
        print(f"  E[{i}] = {w:.12f}   residual {r:.2e}")

    if args.observables and cfg.observables and evecs_hashed is not None:
        # Shard-native observables: |ψ₀⟩ never leaves the hashed space.
        # Every observable engine shares H's mesh and hash layout (pure
        # functions of the basis + device count), so the hashed ψ is
        # directly consumable — no block-order psi, no layout
        # materialization, no global array at any point.  The binding +
        # state-form algebra lives in models/observables (shared with
        # the dynamics solvers, DESIGN.md §29).
        from distributed_matvec_tpu.io.hdf5 import save_observables
        from distributed_matvec_tpu.models.observables import (
            expectations as _expectations)

        with timer.scope("observables"):
            values = _expectations(cfg.observables, eng, evecs_hashed[0],
                                   shards_path=args.shards)
        if rank0:
            for name, val in save_observables(out, values).items():
                print(f"  <{name}> = {val:.12f}")
    elif args.observables and cfg.observables and evec_rows is not None:
        # ⟨ψ₀|O|ψ₀⟩ per observable, printed and saved under /observables —
        # the output group the reference driver creates (Diagonalize.chpl:276-279).
        # Each observable gets its own *fused-mode* engine: no structure
        # build (the ELL pack costs minutes at scale and would be paid per
        # observable), device-speed apply — the analog of the reference
        # keeping observables inside its exported kernels
        # (LatticeSymmetries.chpl:16-31) instead of a host path.
        from distributed_matvec_tpu.io.hdf5 import save_observables

        psi = evec_rows[0]
        xh_cache = {}

        def obs_input(obs):
            """psi in the form the observable's engine consumes.

            A REAL-sector engine cannot carry a complex state — casting
            would silently drop Im(psi) — but for real Hermitian O,
            ψ†Oψ = Re†O·Re + Im†O·Im (the cross terms cancel), so complex
            psi becomes the two-column real batch [Re, Im] and the batched
            dot sums both columns.  A complex-sector engine gets psi
            promoted to complex.
            """
            if obs.effective_is_real:
                if np.iscomplexobj(psi):
                    return np.stack([psi.real, psi.imag], axis=1)
                return psi
            return psi.astype(np.complex128)

        def expectation(obs):
            p = obs_input(obs)
            if hasattr(eng, "from_hashed"):
                from distributed_matvec_tpu.parallel.distributed import (
                    DistributedEngine)
                # share H's mesh and hash layout (pure functions of the
                # basis + device count) and reuse the shuffled |psi> per
                # engine form — only the fused kernel tables differ per
                # observable.  A shard-native engine's observables come
                # from the SAME shard file (the basis is still never built
                # globally); the layout psi's block form already required
                # is shared, not rebuilt.
                if args.shards:
                    oeng = DistributedEngine.from_shards(
                        obs, args.shards, mesh=eng.mesh, mode="fused")
                    oeng.layout = eng._require_layout()
                else:
                    oeng = DistributedEngine(obs, mesh=eng.mesh,
                                             mode="fused", layout=eng.layout)
                key = (oeng.pair, p.dtype.kind, p.ndim)
                if key not in xh_cache:
                    xh_cache[key] = oeng.to_hashed(p)
                xh = xh_cache[key]
                # a [Re, Im] batch's dot sums both columns — exactly the
                # two needed terms
                return float(np.real(complex(oeng.dot(xh, oeng.matvec(xh)))))
            from distributed_matvec_tpu.parallel.engine import LocalEngine
            oeng = LocalEngine(obs, mode="fused")
            y = np.asarray(oeng.matvec(p))
            return float(np.real(np.vdot(p, y)))

        with timer.scope("observables"):
            values = [(obs.name or f"observable_{k}", expectation(obs))
                      for k, obs in enumerate(cfg.observables)]
        if rank0:
            for name, val in save_observables(out, values).items():
                print(f"  <{name}> = {val:.12f}")

    # phase timings + registry totals into the same stream the engines and
    # solvers wrote, then flush — the run dir is self-contained for
    # `obs_report summarize` the moment the process exits
    timer.emit(app="diagonalize")
    obs.emit("metrics_snapshot", metrics=obs.snapshot())
    obs.flush()
    timer.report()
    if watchdog is not None:
        watchdog.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
