#!/usr/bin/env python
"""Solve service: drain (or keep serving) a spool of diagonalize jobs.

The process behind the job-stream layer (DESIGN.md §26,
``distributed_matvec_tpu/serve/``): scans ``<serve_dir>/queue/`` for job
specs (written by ``apps/diagonalize.py --submit --serve-dir DIR`` or
any JSON writer), admits them against the calibrated capacity model,
groups same-engine jobs, batches each group through ``lanczos_block``'s
multi-RHS path over a warm LRU engine pool, and writes per-job results
into ``<serve_dir>/done/<job_id>.json``.

Exit-code contract (shared with diagonalize — a supervisor treats both
the same way):

* ``0``   — drained (``--drain``) or stopped after ``--max-idle-s``.
* ``75``  — preempted (SIGTERM/SIGINT latched): the running batch exits
  at its next block boundary, every in-flight job is respooled as
  queued, telemetry is flushed.  Relaunch with the same argv to resume
  the undone work.
* ``76``  — stalled (a wedged peer tripped the heartbeat watchdog in a
  multi-process deployment).

Usage::

    python apps/solve_service.py /path/to/spool --drain
    python apps/solve_service.py /path/to/spool --max-idle-s 300 \\
        --obs-dir /tmp/serve_run
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from distributed_matvec_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="exit codes: 0 drained/idle-stopped, 75 preempted "
               "(relaunch the same argv to resume), 76 stalled")
    ap.add_argument("serve_dir", help="spool directory (queue/ + done/)")
    ap.add_argument("--drain", action="store_true",
                    help="exit 0 once the queue is empty instead of "
                         "polling for new submissions")
    ap.add_argument("--poll-s", type=float, default=0.5,
                    help="spool scan interval while idle (default 0.5)")
    ap.add_argument("--max-idle-s", type=float, default=None,
                    help="stop after this much continuous idleness "
                         "(default: serve forever)")
    ap.add_argument("--block-width", type=int, default=None,
                    help="max jobs batched into one lanczos_block call "
                         "(default: config serve_block_width)")
    ap.add_argument("--pool-gb", type=float, default=None,
                    help="engine-pool byte budget in GB (default: config "
                         "serve_pool_gb)")
    ap.add_argument("--hbm-gb", type=float, default=16.0,
                    help="device-memory budget admission prices against")
    ap.add_argument("--host-ram-gb", type=float, default=64.0,
                    help="host-RAM budget for streamed-mode plans")
    ap.add_argument("--calibration", default=None, metavar="PATH",
                    help="rate-calibration JSON (tools/gather_bound.py); "
                         "default: the content-addressed sidecar when "
                         "present — admission ETAs are unpriced without "
                         "one")
    ap.add_argument("--obs-dir", default=None, metavar="DIR",
                    help="telemetry run directory (job_event/admission/"
                         "engine_pool events; `obs_report watch DIR` "
                         "renders the live queue panel)")
    ap.add_argument("--port", type=int, default=None, metavar="PORT",
                    help="serve GET /metrics (OpenMetrics) and "
                         "GET /healthz on this port (default: "
                         "DMT_OBS_PORT + rank when set, else no "
                         "endpoint; 0 binds an ephemeral port)")
    args = ap.parse_args(argv)

    from distributed_matvec_tpu import obs
    from distributed_matvec_tpu.serve import (EnginePool, JobQueue,
                                              Scheduler, SolveService)
    from distributed_matvec_tpu.utils.config import update_config

    if args.obs_dir:
        update_config(obs_dir=args.obs_dir)

    # crash observability BEFORE any heavy work: fatal-signal tracebacks
    # land in the postmortem dir, and the scrape endpoint is live while
    # the pool warms (liveness probes must not wait for the first batch)
    obs.install_fatal_handlers()
    server = obs.start_exporter(port=args.port)

    with obs.span("solve_service", kind="run"):
        pool = EnginePool(max_bytes=int(args.pool_gb * 1e9)
                          if args.pool_gb is not None else None)
        sched = Scheduler(queue=JobQueue(args.serve_dir), pool=pool,
                          calibration_path=args.calibration,
                          block_width=args.block_width,
                          hbm_gb=args.hbm_gb,
                          host_ram_gb=args.host_ram_gb)
        rc = SolveService(args.serve_dir, scheduler=sched,
                          poll_s=args.poll_s).run(
            drain=args.drain, max_idle_s=args.max_idle_s)
    obs.emit("metrics_snapshot", metrics=obs.snapshot())
    obs.write_textfile()       # the textfile rank 0's /metrics aggregates
    obs.flush()
    if server is not None:
        obs.stop_exporter()
    return rc


if __name__ == "__main__":
    sys.exit(main())
