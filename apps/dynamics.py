#!/usr/bin/env python
"""Dynamics: YAML model in → KPM spectral density or exp(-iHt) trajectory.

The dynamics-family driver beside ``apps/diagonalize.py`` (DESIGN.md
§29): the same engine stack (ell / streamed / hybrid, ``--devices``
meshes), the same exit-code contract, but the solve is Chebyshev/KPM
moments + a kernel-reconstructed density of states (``--solver kpm``)
or Krylov time evolution with drift telemetry and optional per-step
observable trajectories (``--solver evolve``).

Usage:
    python apps/dynamics.py model.yaml --solver kpm --moments 256 -o dos.h5
    python apps/dynamics.py model.yaml --solver evolve --t-final 5 \
        --observables --checkpoint traj.h5
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

from distributed_matvec_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()


def main(argv=None):
    from distributed_matvec_tpu.obs import trace as _trace

    with _trace.span("dynamics", kind="run"):
        return _main(argv)


def _main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="exit codes: 0 solved, 2 bad config/arguments, "
               "75 preempted (SIGTERM/SIGINT latched; with --checkpoint "
               "the trajectory/moment state was written at the last "
               "step boundary — relaunch the SAME argv to resume, "
               "bit-consistent with an uninterrupted run), 76 stalled "
               "(heartbeat watchdog).  A supervisor should retry 75/76 "
               "and treat other nonzero codes as permanent — the same "
               "contract as apps/diagonalize.py.")
    ap.add_argument("input", help="YAML config (data/*.yaml schema)")
    ap.add_argument("--solver", choices=("kpm", "evolve"), default="kpm",
                    help="dynamics solver: Chebyshev/KPM spectral "
                         "density (kpm) or Krylov exp(-iHt) time "
                         "evolution (evolve); eigenpair solves live in "
                         "apps/diagonalize.py")
    ap.add_argument("-o", "--output", default=None,
                    help="output HDF5 (default: <input>.dyn.h5)")
    ap.add_argument("--mode", choices=("ell", "compact", "streamed",
                                       "fused", "hybrid"),
                    default="streamed",
                    help="engine mode (default streamed: the plan is "
                         "resolved once and re-streamed per apply — the "
                         "regime repeated-matvec dynamics amortizes "
                         "best)")
    ap.add_argument("--devices", type=int, default=0,
                    help="shard over an n-device mesh (0 = one device)")
    # -- kpm ---------------------------------------------------------------
    ap.add_argument("--moments", type=int, default=256,
                    help="kpm: Chebyshev moment count (energy "
                         "resolution ~ pi*spectral_halfwidth/moments)")
    ap.add_argument("--vectors", type=int, default=4,
                    help="kpm: stochastic-trace random vectors (error "
                         "~ 1/sqrt(n_states*vectors))")
    ap.add_argument("--kernel", choices=("jackson", "lorentz", "none"),
                    default="jackson", help="kpm: damping kernel")
    ap.add_argument("--points", type=int, default=512,
                    help="kpm: energy-grid points for the DOS")
    ap.add_argument("--bounds-iters", type=int, default=64,
                    help="kpm: Lanczos iterations for the spectral "
                         "bracket")
    # -- evolve ------------------------------------------------------------
    ap.add_argument("--t-final", type=float, default=1.0,
                    help="evolve: trajectory length")
    ap.add_argument("--dt0", type=float, default=None,
                    help="evolve: initial adaptive step (default "
                         "t_final/16)")
    ap.add_argument("--krylov-dim", type=int, default=24,
                    help="evolve: per-step Krylov dimension")
    ap.add_argument("--tol", type=float, default=1e-12,
                    help="evolve: local-error budget per unit time")
    ap.add_argument("--observables", action="store_true",
                    help="evolve: record <psi|O|psi> trajectories for "
                         "the YAML observables (bound fused-mode "
                         "engines sharing the basis artifacts)")
    # -- shared ------------------------------------------------------------
    ap.add_argument("--seed", type=int, default=42,
                    help="start-state / random-vector seed")
    ap.add_argument("--checkpoint", default=None, metavar="CKPT_H5",
                    help="mid-run checkpoint/resume file: the solver "
                         "state is written at step boundaries and on "
                         "preemption; a rerun with the same argv "
                         "resumes bit-consistently")
    ap.add_argument("--checkpoint-every", type=int, default=16,
                    help="checkpoint cadence in solver steps")
    ap.add_argument("--obs-dir", default=None, metavar="DIR",
                    help="telemetry run directory (DMT_OBS_DIR)")
    ap.add_argument("--job-id", default=None, metavar="ID",
                    help="job-namespacing id (DMT_JOB_ID)")
    args = ap.parse_args(argv)

    from distributed_matvec_tpu import obs
    from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml
    from distributed_matvec_tpu.utils.config import update_config

    if args.obs_dir:
        update_config(obs_dir=args.obs_dir)
    if args.job_id:
        os.environ["DMT_JOB_ID"] = args.job_id
        update_config(job_id=args.job_id)

    import signal as _signal

    from distributed_matvec_tpu.utils import preempt as _preempt
    from distributed_matvec_tpu.utils.preempt import (EXIT_PREEMPTED,
                                                      Preempted)
    _preempt.ensure_installed(signals=(_signal.SIGTERM, _signal.SIGINT))

    out = args.output or os.path.splitext(args.input)[0] + ".dyn.h5"
    obs.emit("run_start", app="dynamics", input=args.input, output=out,
             solver=args.solver, mode=args.mode, devices=args.devices)

    cfg = load_config_from_yaml(args.input, hamiltonian=True,
                                observables=args.observables)
    if cfg.hamiltonian is None:
        print("config has no hamiltonian section", file=sys.stderr)
        return 2
    if not cfg.hamiltonian.effective_is_real:
        from distributed_matvec_tpu.parallel.engine import use_pair_complex
        if use_pair_complex():
            print("dynamics solvers do not support pair-form complex "
                  "sectors (no J-aware recurrence) — run the sector "
                  "native-c128 on CPU", file=sys.stderr)
            return 2

    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    eng = DistributedEngine(cfg.hamiltonian,
                            n_devices=args.devices or 1, mode=args.mode)
    n = eng.n_states
    print(f"basis: N={n} states, engine mode={args.mode}")

    t0 = time.perf_counter()
    try:
        if args.solver == "kpm":
            from distributed_matvec_tpu.solve import (kpm_moments,
                                                      reconstruct_dos)
            res = kpm_moments(
                eng.matvec, n_moments=args.moments,
                n_vectors=args.vectors, seed=args.seed,
                bounds_iters=args.bounds_iters,
                checkpoint_path=args.checkpoint,
                checkpoint_every=args.checkpoint_every)
            energies, rho = reconstruct_dos(
                res.moments, res.scale, npoints=args.points,
                kernel=args.kernel)
            dt = time.perf_counter() - t0
            print(f"kpm: {args.moments} moments ({res.num_applies} "
                  f"applies) in {dt:.2f}s "
                  f"({res.steady_moments_per_s:.1f} moments/s steady)")
            print(f"  spectral bracket [{res.bounds[0]:.6f}, "
                  f"{res.bounds[1]:.6f}]")
            payload = {"moments": res.moments,
                       "moment_stderr": res.moment_stderr,
                       "energies": energies, "dos": rho,
                       "bounds": np.asarray(res.bounds),
                       "scale": np.asarray(res.scale)}
        else:
            from distributed_matvec_tpu.solve import krylov_evolve
            bound = []
            if args.observables and cfg.observables:
                from distributed_matvec_tpu.models.observables import (
                    bind_observables)
                bound = bind_observables(cfg.observables, eng)
            res = krylov_evolve(
                eng.matvec, t_final=args.t_final, dt0=args.dt0,
                krylov_dim=args.krylov_dim, tol=args.tol,
                seed=args.seed, observables=bound,
                checkpoint_path=args.checkpoint,
                checkpoint_every=args.checkpoint_every)
            dt = time.perf_counter() - t0
            if res.resumed_from:
                print(f"solver: resumed from {res.resumed_from} "
                      "checkpointed steps")
            print(f"evolve: t={res.times[-1]:.6f} in {res.num_steps} "
                  f"steps / {res.num_applies} applies in {dt:.2f}s "
                  f"({res.steady_steps_per_s:.2f} steps/s steady)")
            print(f"  norm drift {res.norm_drift:.3e}, energy drift "
                  f"{res.energy_drift:.3e}")
            payload = {"times": res.times, "energies": res.energies,
                       "norm_drift": np.float64(res.norm_drift),
                       "energy_drift": np.float64(res.energy_drift)}
            for name, series in (res.observables or {}).items():
                payload[f"obs_{name}_t"] = np.asarray(
                    [t for t, _ in series])
                payload[f"obs_{name}"] = np.asarray(
                    [v for _, v in series])
                print(f"  <{name}>(t={series[-1][0]:.4f}) = "
                      f"{series[-1][1]:.12f}")
    except Preempted as e:
        print(f"preempted: {e}", file=sys.stderr)
        obs.emit("run_preempted", app="dynamics", solver=e.solver,
                 iters=int(e.iters), checkpoint=e.checkpoint_path or "",
                 exit_code=EXIT_PREEMPTED)
        obs.emit("metrics_snapshot", metrics=obs.snapshot())
        obs.flush()
        return EXIT_PREEMPTED

    import h5py

    with h5py.File(out, "a") as f:
        grp = f.require_group(args.solver)
        for key, val in payload.items():
            if key in grp:
                del grp[key]
            grp.create_dataset(key, data=val)
    print(f"wrote /{args.solver} -> {out}")
    obs.emit("dynamics_result", solver=args.solver,
             **{k: (float(v) if np.ndim(v) == 0 else int(np.size(v)))
                for k, v in payload.items()})
    obs.emit("metrics_snapshot", metrics=obs.snapshot())
    obs.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
